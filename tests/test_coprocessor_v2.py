"""CoprocessorV2: typed-schema filter/projection/aggregation pushdown
(reference coprocessor_v2.h + aggregation.h; scan-with-coprocessor suites
under test/unit_test/misc/)."""

import numpy as np
import pytest

from dingo_tpu.coprocessor.coprocessor_v2 import (
    AggOpV2,
    AggregationSpec,
    CoprocessorDef,
    CoprocessorError,
    CoprocessorV2,
    SchemaColumn,
    decode_row,
    encode_row,
)

SCHEMA = [
    SchemaColumn("id", "BIGINT", 0),
    SchemaColumn("dept", "VARCHAR", 1),
    SchemaColumn("salary", "DOUBLE", 2),
    SchemaColumn("active", "BOOL", 3),
]

ROWS = [
    [1, "eng", 100.0, True],
    [2, "eng", 150.0, True],
    [3, "ops", 90.0, False],
    [4, "ops", None, True],
    [5, "hr", 120.0, True],
]


def kvs():
    return [(f"k{r[0]}".encode(), encode_row(r)) for r in ROWS]


def test_row_roundtrip():
    for r in ROWS:
        assert decode_row(encode_row(r), 4) == r


def test_filter_and_projection():
    cop = CoprocessorV2(CoprocessorDef(
        original_schema=SCHEMA,
        selection=[1, 2],
        filter_expr=["and", ["eq", ["field", "active"], ["const", True]],
                     ["ge", ["field", "salary"], ["const", 100.0]]],
    ))
    out = cop.execute(kvs())
    assert [k for k, _ in out] == [b"k1", b"k2", b"k5"]
    assert decode_row(out[0][1], 2) == ["eng", 100.0]


def test_group_by_aggregation():
    cop = CoprocessorV2(CoprocessorDef(
        original_schema=SCHEMA,
        group_by=[1],
        aggregations=[
            AggregationSpec(AggOpV2.COUNT, -1),
            AggregationSpec(AggOpV2.SUM, 2),
            AggregationSpec(AggOpV2.MAX, 2),
            AggregationSpec(AggOpV2.COUNT_WITH_NULL, 2),
        ],
    ))
    out = dict(cop.execute(kvs()))
    eng = decode_row(out[encode_row(["eng"])], 4)
    assert eng == [2, 250.0, 150.0, 2]
    ops = decode_row(out[encode_row(["ops"])], 4)
    # SUM skips the NULL salary; COUNT(*) counts both rows;
    # COUNT_WITH_NULL counts rows regardless of NULL
    assert ops == [2, 90.0, 90.0, 2]


def test_global_aggregation_and_sum0():
    cop = CoprocessorV2(CoprocessorDef(
        original_schema=SCHEMA,
        filter_expr=["eq", ["field", "dept"], ["const", "nope"]],
        aggregations=[AggregationSpec(AggOpV2.SUM0, 2)],
    ))
    out = cop.execute(kvs())
    assert out == []  # no group materialized for an empty result set
    cop2 = CoprocessorV2(CoprocessorDef(
        original_schema=SCHEMA,
        aggregations=[AggregationSpec(AggOpV2.SUM0, 2),
                      AggregationSpec(AggOpV2.MIN, 2)],
    ))
    out = cop2.execute(kvs())
    assert len(out) == 1 and out[0][0] == b""
    assert decode_row(out[0][1], 2) == [460.0, 90.0]


def test_bad_definitions_rejected():
    with pytest.raises(CoprocessorError):
        CoprocessorV2(CoprocessorDef(original_schema=SCHEMA, selection=[9]))
    with pytest.raises(CoprocessorError):
        CoprocessorV2(CoprocessorDef(
            original_schema=SCHEMA,
            aggregations=[AggregationSpec(AggOpV2.SUM, 7)],
        ))


def test_scan_with_coprocessor_over_grpc():
    """KvScan carrying a Coprocessor: filter+project and aggregate paths
    (reference scan-with-coprocessor, scan_manager v2)."""
    import time

    from dingo_tpu.client import DingoClient
    from dingo_tpu.coordinator.control import CoordinatorControl
    from dingo_tpu.coordinator.kv_control import KvControl
    from dingo_tpu.coordinator.tso import TsoControl
    from dingo_tpu.engine.raw_engine import MemEngine
    from dingo_tpu.raft import LocalTransport, wire
    from dingo_tpu.server import pb
    from dingo_tpu.server.rpc import DingoServer
    from dingo_tpu.store.node import StoreNode

    me = MemEngine()
    control = CoordinatorControl(me, replication=1)
    cs = DingoServer()
    cs.host_coordinator_role(control, TsoControl(me), KvControl(me))
    cport = cs.start()
    node = StoreNode("s0", LocalTransport(), control, raft_kw={"seed": 0})
    srv = DingoServer()
    srv.host_store_role(node)
    port = srv.start()
    node.start_heartbeat(0.1)
    client = DingoClient(f"127.0.0.1:{cport}", {"s0": f"127.0.0.1:{port}"})
    try:
        req = pb.CreateRegionRequest()
        req.range.start_key = b"r"
        req.range.end_key = b"s"
        assert client.coordinator.CreateRegion(req).error.errcode == 0
        time.sleep(1.0)
        for k, v in kvs():
            client.kv_put(b"r/" + k, v)

        sreq = pb.KvScanRequest()
        d = client._region_for_key(b"r/")
        sreq.context.region_id = d.region_id
        sreq.range.start_key = b"r"
        sreq.range.end_key = b"s"
        for c in SCHEMA:
            col = sreq.coprocessor.original_schema.add()
            col.name, col.sql_type, col.index = c.name, c.sql_type, c.index
        sreq.coprocessor.selection.extend([0, 2])
        sreq.coprocessor.filter_expr = wire.encode(
            ["gt", ["field", "salary"], ["const", 95.0]]
        )
        resp = client._call_leader(d, "StoreService", "KvScan", sreq)
        assert resp.error.errcode == 0
        got = [decode_row(kv.value, 2) for kv in resp.kvs]
        assert got == [[1, 100.0], [2, 150.0], [5, 120.0]]

        # aggregation arm
        areq = pb.KvScanRequest()
        areq.context.region_id = d.region_id
        areq.range.start_key = b"r"
        areq.range.end_key = b"s"
        for c in SCHEMA:
            col = areq.coprocessor.original_schema.add()
            col.name, col.sql_type, col.index = c.name, c.sql_type, c.index
        areq.coprocessor.group_by.append(1)
        a = areq.coprocessor.aggregations.add()
        a.op, a.column_index = 2, -1  # COUNT(*)
        resp = client._call_leader(d, "StoreService", "KvScan", areq)
        counts = {kv.key: decode_row(kv.value, 1)[0] for kv in resp.kvs}
        assert counts[encode_row(["eng"])] == 2
        assert counts[encode_row(["ops"])] == 2
        assert counts[encode_row(["hr"])] == 1
    finally:
        client.close()
        srv.stop()
        cs.stop()
        node.stop()
