"""Batched distance kernels for the MXU.

TPU-native replacement for the reference's per-pair SIMD hooks
(src/simd/hook.h:23-31: fvec_L2sqr, fvec_inner_product, fvec_L1, fvec_Linf,
fvec_norm_L2sqr, fvec_L2sqr_ny, fvec_inner_products_ny, fvec_madd, ...) and
the faiss distance backends used by VectorIndexFlat / IvfFlat / IvfPq
(reference src/vector/vector_index_flat.cc, vector_index_utils.h:43-160
CalcDistanceEntry).

Design: the reference computes one scalar distance per (query, vector) pair in
an AVX loop; on TPU the whole [batch, n] distance matrix is one matmul:

    L2sqr(q, x)  = ||q||^2 - 2 q.x + ||x||^2     (one einsum + rank-1 adds)
    IP(q, x)     =  q.x
    cosine(q, x) =  q.x / (||q|| ||x||)          (normalize, then IP)
    hamming(a,b) = (nbits - pm(a).pm(b)) / 2     (pm: bits -> +/-1 floats,
                                                  so binary distance is ALSO
                                                  an MXU matmul)

All functions accept an optional precomputed ``x_sqnorm`` so indexes can cache
database norms (the reference caches nothing — faiss recomputes; caching is
free QPS on TPU).

Score convention: ``score_matrix`` returns "larger is better" scores for every
metric (negated L2) so a single top-k kernel serves all metrics;
``scores_to_distances`` converts back to the faiss/dingo wire convention
(L2: squared distance ascending; IP/cosine: similarity descending — see
reference vector_index_utils.h FillSearchResult).
"""

from __future__ import annotations

import enum
from typing import Optional

import jax
import jax.numpy as jnp

#: Matmul precision for distance contractions. On TPU the default matmul
#: precision is bf16 which costs recall (measured: flat recall@10 0.9875 vs
#: 1.0, PQ encode collapses); HIGHEST keeps f32 accumulation on the MXU.
#: Index configs may pass precision="default" for the big [b, n] scan when
#: the recall budget allows trading exactness for ~4x matmul throughput.
PRECISION = jax.lax.Precision.HIGHEST


class Metric(enum.Enum):
    """Mirrors pb::common::MetricType (METRIC_TYPE_L2 / _INNER_PRODUCT /
    _COSINE) plus HAMMING for the binary index family
    (reference vector_index_flat.h binary variant via faiss::IndexBinary)."""

    L2 = "l2"
    INNER_PRODUCT = "ip"
    COSINE = "cosine"
    HAMMING = "hamming"


def squared_norms(x: jax.Array) -> jax.Array:
    """||x_i||^2 per row. Replacement for fvec_norm_L2sqr (src/simd/hook.h:27)."""
    x = x.astype(jnp.float32)
    return jnp.einsum("nd,nd->n", x, x, precision=PRECISION)


def _dot(q: jax.Array, x: jax.Array, precision=None) -> jax.Array:
    """[b,d] @ [n,d]^T with f32 accumulation regardless of storage dtype.

    bf16-resident databases (the bf16 precision tier) pair the query down
    to bf16 so the contraction is a native bf16 MXU matmul instead of XLA
    materializing an f32 upcast of the whole [n, d] operand; accumulation
    stays f32 via preferred_element_type either way."""
    if x.dtype == jnp.bfloat16:
        q = q.astype(jnp.bfloat16)
    return jnp.einsum(
        "bd,nd->bn",
        q,
        x,
        preferred_element_type=jnp.float32,
        precision=PRECISION if precision is None else precision,
    )


def pairwise_l2sqr(
    q: jax.Array,
    x: jax.Array,
    x_sqnorm: Optional[jax.Array] = None,
    precision=None,
) -> jax.Array:
    """Squared L2 distance matrix [b, n]. Replaces fvec_L2sqr / fvec_L2sqr_ny
    (src/simd/hook.h:23,28); faiss METRIC_L2 convention (squared, ascending)."""
    if x_sqnorm is None:
        x_sqnorm = squared_norms(x)
    q_sqnorm = squared_norms(q)
    d = q_sqnorm[:, None] - 2.0 * _dot(q, x, precision) + x_sqnorm[None, :]
    # Guard tiny negatives from cancellation so downstream sqrt/compare is safe.
    return jnp.maximum(d, 0.0)


def pairwise_inner_product(
    q: jax.Array, x: jax.Array, precision=None
) -> jax.Array:
    """Inner-product similarity matrix [b, n] (descending = better).
    Replaces fvec_inner_product / fvec_inner_products_ny (src/simd/hook.h:24,29)."""
    return _dot(q, x, precision)


def normalize(x: jax.Array, eps: float = 1e-30) -> jax.Array:
    """Row L2-normalization (reference VectorIndexUtils normalization,
    vector_index_utils.h:183-184 — applied for COSINE metric)."""
    x32 = x.astype(jnp.float32)
    n = jnp.sqrt(jnp.maximum(squared_norms(x32), eps))
    return (x32 / n[:, None]).astype(x.dtype)


def np_normalize(x, eps: float = 1e-30):
    """Host-side counterpart of ``normalize`` with the SAME epsilon
    convention (floor on the SQUARED norm): cosine rows must normalize
    to the same values no matter which side of the H2D boundary prepped
    them — index families post-filter and parity-check each other, so
    one divergent near-zero-row convention shows up as a ranking flake.
    Pure numpy: no device round-trip on the write/search prep path."""
    import numpy as np

    x = np.ascontiguousarray(x, np.float32)
    n = np.sqrt(np.maximum((x * x).sum(axis=1, dtype=np.float32), eps))
    return np.ascontiguousarray(x / n[:, None])


def pairwise_cosine(
    q: jax.Array,
    x: jax.Array,
    x_is_normalized: bool = False,
    x_sqnorm: Optional[jax.Array] = None,
    precision=None,
) -> jax.Array:
    """Cosine similarity matrix [b, n] (descending = better)."""
    qn = normalize(q)
    if x_is_normalized:
        return _dot(qn, x, precision)
    if x_sqnorm is None:
        x_sqnorm = squared_norms(x)
    inv = jax.lax.rsqrt(jnp.maximum(x_sqnorm, 1e-30))
    return _dot(qn, x, precision) * inv[None, :]


def bits_to_pm1(packed: jax.Array, nbits: int) -> jax.Array:
    """Unpack uint8-packed bits [n, nbytes] -> +/-1 float matrix [n, nbits].

    This is the trick that moves hamming distance onto the MXU:
    hamming(a, b) = (nbits - <pm(a), pm(b)>) / 2.
    """
    n, nbytes = packed.shape
    shifts = jnp.arange(8, dtype=packed.dtype)
    bits = (packed[:, :, None] >> shifts[None, None, :]) & 1  # [n, nbytes, 8]
    bits = bits.reshape(n, nbytes * 8)[:, :nbits]
    return (bits.astype(jnp.float32) * 2.0 - 1.0)


def pairwise_hamming(
    q_packed: jax.Array, x_packed: jax.Array, nbits: int, precision=None
) -> jax.Array:
    """Hamming distance matrix [b, n] (ascending = better) over uint8-packed
    bit vectors. Binary-index replacement for faiss::IndexBinaryFlat search."""
    qp = bits_to_pm1(q_packed, nbits)
    xp = bits_to_pm1(x_packed, nbits)
    return (nbits - _dot(qp, xp, precision)) * 0.5


def metric_ascending(metric: Metric) -> bool:
    """True when smaller distance means better (L2, hamming)."""
    return metric in (Metric.L2, Metric.HAMMING)


def score_matrix(
    q: jax.Array,
    x: jax.Array,
    metric: Metric,
    x_sqnorm: Optional[jax.Array] = None,
    x_is_normalized: bool = False,
    nbits: int = 0,
    precision=None,
) -> jax.Array:
    """Unified 'larger is better' score matrix for all metrics, so one top-k
    kernel (ops/topk.py) serves the whole index family."""
    if metric is Metric.L2:
        return -pairwise_l2sqr(q, x, x_sqnorm, precision)
    if metric is Metric.INNER_PRODUCT:
        return pairwise_inner_product(q, x, precision)
    if metric is Metric.COSINE:
        return pairwise_cosine(q, x, x_is_normalized, x_sqnorm, precision)
    if metric is Metric.HAMMING:
        return -pairwise_hamming(q, x, nbits, precision)
    raise ValueError(f"unknown metric {metric}")


def scores_to_distances(scores: jax.Array, metric: Metric) -> jax.Array:
    """Convert internal scores back to the faiss/dingo wire convention
    (pb::index::VectorWithDistance.distance)."""
    if metric_ascending(metric):
        return -scores
    return scores


def device_wait_span(name: str, value):
    """Trace hook for device dispatch sites: when the current trace is
    sampled, block until `value` (any jax pytree) is ready inside an
    ``ops.<name>`` span, so the span measures real kernel time instead of
    async-dispatch time. Otherwise value passes through untouched — one
    sampled-check, no synchronization, no allocation (the span name is
    only built once the check passes); a dispatch with no surrounding
    request trace is never timed, so background kernels don't mint
    single-span root traces."""
    from dingo_tpu.trace import TRACER, current_span

    cur = current_span()
    if cur is None or not cur.sampled:
        return value
    with TRACER.start_span("ops." + name):
        jax.block_until_ready(value)
    return value
