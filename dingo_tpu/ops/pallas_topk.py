"""Fused distance + running top-k Pallas kernel.

SURVEY.md §7 kernel layer: "fused distance+top-k Pallas kernel with running
k-selection to avoid materializing [b, n]". The XLA path (ops/distance.py +
lax.top_k) materializes the full [b, n] score matrix in HBM; this kernel
streams the database through VMEM in blocks, keeps a [b, k] running best in
VMEM scratch, and never writes the score matrix out — at 10M x 768 that is
~2.5 GB of HBM traffic saved per query batch (k=10, b=64).

Selection strategy: per block, k rounds of (max, argmax, mask) over the
[b, C] block scores — k/d ≈ 1-2% overhead relative to the distance matmul —
then a merge of the 2k running+block candidates by another k rounds.
Runs under interpret=True on CPU for tests; compiled on TPU.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from dingo_tpu.obs.sentinel import sentinel_jit

NEG_INF = float("-inf")


def _select_topk(scores, idx, k):
    """k rounds of max/argmax/mask over [b, C] -> ([b, k], [b, k]).

    The winner's id is extracted with a masked max reduction rather than
    take_along_axis: Mosaic's gather lowering only accepts indices shaped
    operand+(1,), so a [b,1] gather on [b,C] fails to lower (observed
    on-chip round 3) — and a where+max over the one matching lane is
    vector-unit work anyway, no gather needed.
    """
    vals, ids = [], []
    b, c = scores.shape
    cols = jax.lax.broadcasted_iota(jnp.int32, (b, c), 1)
    for _ in range(k):
        m = jnp.max(scores, axis=1)                      # [b]
        am = jnp.argmax(scores, axis=1)                  # [b]
        hit = cols == am[:, None]
        ids.append(jnp.max(
            jnp.where(hit, idx, jnp.int32(np.iinfo(np.int32).min)), axis=1
        ))
        vals.append(m)
        # mask the winner out
        scores = jnp.where(hit, NEG_INF, scores)
    return jnp.stack(vals, axis=1), jnp.stack(ids, axis=1)


def _fused_kernel(q_ref, qsq_ref, x_ref, xsq_ref, valid_ref,
                  out_v_ref, out_i_ref, best_v, best_i, *, k, block, ascending):
    j = pl.program_id(0)
    nblocks = pl.num_programs(0)

    @pl.when(j == 0)
    def _init():
        best_v[:] = jnp.full_like(best_v, NEG_INF)
        best_i[:] = jnp.full_like(best_i, -1)

    q = q_ref[:]                                          # [b, d]
    x = x_ref[:].astype(jnp.float32)   # bf16 stores promote in VMEM
    # HIGHEST precision: the default bf16-pass matmul measurably costs
    # recall (distance.py pins the same; flat recall@10 0.9875 -> 1.0).
    dots = jax.lax.dot_general(
        q, x, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )                                                     # [b, C]
    if ascending:  # L2: score = -(||q||^2 - 2qx + ||x||^2)
        scores = -(qsq_ref[:] - 2.0 * dots + xsq_ref[:])  # [b,1] + [1,C]
    else:          # IP
        scores = dots
    valid = valid_ref[:]                                  # [1, C] float (1/0)
    scores = jnp.where(valid > 0.5, scores, NEG_INF)

    b = scores.shape[0]
    gidx = (
        jax.lax.broadcasted_iota(jnp.int32, (b, block), 1) + j * block
    )
    blk_v, blk_i = _select_topk(scores, gidx, k)

    cat_v = jnp.concatenate([best_v[:], blk_v], axis=1)   # [b, 2k]
    cat_i = jnp.concatenate([best_i[:], blk_i], axis=1)
    new_v, new_i = _select_topk(cat_v, cat_i, k)
    best_v[:] = new_v
    best_i[:] = new_i

    @pl.when(j == nblocks - 1)
    def _finish():
        fv = best_v[:]
        out_v_ref[:] = fv
        # -inf picks are argmax-of-all-masked artifacts: they carry real
        # (and duplicated) slot ids. Map them to -1 like the XLA path
        # (topk.py maps -inf picks to -1) so filter-excluded ids never leak.
        out_i_ref[:] = jnp.where(jnp.isneginf(fv), -1, best_i[:])


@sentinel_jit("ops.pallas.fused_topk",
              static_argnames=("k", "block", "ascending", "interpret"))
def fused_topk(
    q: jax.Array,
    x: jax.Array,
    x_sqnorm: jax.Array,
    valid: jax.Array,
    k: int,
    block: int = 2048,
    ascending: bool = True,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Streaming fused search: q[b,d] vs x[n,d] -> (scores[b,k], slots[b,k]).

    Returns 'larger is better' scores (negated L2 when ascending) and global
    slot indices (-1 for masked). n must be a multiple of `block` (pad with
    valid=0 rows).
    """
    b, d = q.shape
    n = x.shape[0]
    assert n % block == 0, f"n={n} not a multiple of block={block}"
    qsq = jnp.einsum("bd,bd->b", q.astype(jnp.float32), q.astype(jnp.float32),
                     precision=jax.lax.Precision.HIGHEST)[:, None]   # [b, 1]
    grid = (n // block,)
    out_v, out_i = pl.pallas_call(
        functools.partial(_fused_kernel, k=k, block=block,
                          ascending=ascending),
        grid=grid,
        in_specs=[
            pl.BlockSpec((b, d), lambda j: (0, 0)),         # q (all blocks)
            pl.BlockSpec((b, 1), lambda j: (0, 0)),         # qsq [b,1]
            pl.BlockSpec((block, d), lambda j: (j, 0)),     # x block
            pl.BlockSpec((1, block), lambda j: (0, j)),     # xsq [1, n]
            pl.BlockSpec((1, block), lambda j: (0, j)),     # valid [1, n]
        ],
        out_specs=[
            pl.BlockSpec((b, k), lambda j: (0, 0)),
            pl.BlockSpec((b, k), lambda j: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, k), jnp.float32),
            jax.ShapeDtypeStruct((b, k), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((b, k), jnp.float32),
            pltpu.VMEM((b, k), jnp.int32),
        ],
        interpret=interpret,
    )(q.astype(jnp.float32), qsq, x, x_sqnorm[None, :],
      valid.astype(jnp.float32)[None, :])
    return out_v, out_i


def fused_search(
    q: np.ndarray,
    x: jax.Array,
    x_sqnorm: jax.Array,
    valid: jax.Array,
    k: int,
    block: int = 2048,
    ascending: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Host-friendly wrapper: pads n to the block multiple and picks
    interpret mode off-TPU (Mosaic kernels only compile for TPU)."""
    n = x.shape[0]
    pad = (-n) % block
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad, x.shape[1]), x.dtype)])
        x_sqnorm = jnp.concatenate([x_sqnorm, jnp.zeros((pad,), x_sqnorm.dtype)])
        valid = jnp.concatenate([valid, jnp.zeros((pad,), valid.dtype)])
    interpret = jax.default_backend() not in ("tpu", "axon")
    return fused_topk(jnp.asarray(q), x, x_sqnorm, valid, k=k, block=block,
                      ascending=ascending, interpret=interpret)


#: stats output lane width (TPU lane tile; only the first 4 lanes carry)
STATS_PAD = 128


def _pruned_fused_kernel(q_ref, qsq_ref, qpsq_ref, x_ref, bsq_ref, xsq_ref,
                         valid_ref, *rest, k, block, nblk, check_every,
                         ascending, sq, inbucket):
    """Dimension-blocked early-pruning whole-index scan (the FLAT arm of
    the PDX scheme — see ops/pallas_ivf._ivf_pruned_kernel for the bound
    math). Grid (row_block j, dim_block jb) with jb INNERMOST: partial
    dots accumulate in VMEM scratch per row block; candidates whose bound
    cannot beat the running k-th best stop contributing, and a row block
    whose candidates are ALL dead (for every query) skips the remaining
    dimension blocks' matmuls.

    Stats output lanes (per query, accumulated): 0 = candidate-block
    pairs scanned, 1 = pairs total, 2 = candidates scanned to the last
    block, 3 = candidates considered."""
    if sq:
        (vmin_ref, scale_ref, out_v_ref, out_i_ref, outs_ref,
         best_v, best_i, cum, alive, xpsq) = rest
    else:
        (out_v_ref, out_i_ref, outs_ref,
         best_v, best_i, cum, alive, xpsq) = rest
    j = pl.program_id(0)
    jb = pl.program_id(1)
    b = cum.shape[0]
    lanes = jax.lax.broadcasted_iota(jnp.int32, (b, STATS_PAD), 1)

    @pl.when((j == 0) & (jb == 0))
    def _init():
        best_v[:] = jnp.full_like(best_v, NEG_INF)
        best_i[:] = jnp.full_like(best_i, -1)
        outs_ref[:] = jnp.zeros_like(outs_ref)

    @pl.when(jb == 0)
    def _init_block():
        cum[:] = jnp.zeros_like(cum)
        xpsq[:] = jnp.zeros_like(xpsq)
        alive[:] = jnp.broadcast_to(valid_ref[:], (b, block))
        nvalid = jnp.sum(valid_ref[:])
        outs_ref[:] += jnp.where(
            lanes == 1, nvalid * nblk, jnp.where(lanes == 3, nvalid, 0.0)
        )

    per_q = jnp.sum(alive[:], axis=1, keepdims=True)       # [b, 1]
    outs_ref[:] += jnp.where(lanes == 0, per_q, 0.0)

    @pl.when(jb == nblk - 1)
    def _count_full():
        outs_ref[:] += jnp.where(lanes == 2, per_q, 0.0)

    @pl.when(jnp.sum(alive[:]) > 0.5)
    def _compute():
        q = q_ref[:]                                       # [b, dblk]
        x = x_ref[0]                                       # [block, dblk]
        if sq:
            # decode f32 -> bf16 multiplies, f32 accumulate (the sq8
            # tier's compute contract, ops/sq.py)
            x = (
                x.astype(jnp.float32) * scale_ref[:] + vmin_ref[:]
            ).astype(jnp.bfloat16)
            q = q.astype(jnp.bfloat16)
            bf16_mul = True
        else:
            # bf16 stores keep bf16 multiplies with f32 accumulation —
            # the same pairing distance._dot applies on the XLA arm, so
            # the pruned scan ranks identically to the flat kernel
            bf16_mul = x.dtype == jnp.bfloat16
            if bf16_mul:
                q = q.astype(jnp.bfloat16)
            else:
                x = x.astype(jnp.float32)
        dots = jax.lax.dot_general(
            q, x, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=(None if bf16_mul else jax.lax.Precision.HIGHEST),
        )                                                  # [b, block]
        cum[:] += dots
        xpsq[:] += bsq_ref[0]                              # [1, block]
        bound = best_v[:, k - 1:k]                         # [b, 1]
        qtail = jnp.maximum(qsq_ref[:] - qpsq_ref[:], 0.0)  # [b, 1]
        xtail = jnp.maximum(xsq_ref[:] - xpsq[:], 0.0)      # [1, block]
        if ascending:
            partial = qpsq_ref[:] - 2.0 * cum[:] + xpsq[:]
            ub = -partial
            final = ub
        else:
            ub = cum[:] + jnp.sqrt(qtail * xtail)
            final = cum[:]

        @pl.when((jb < nblk - 1)
                 & (jax.lax.rem(jb + 1, check_every) == 0))
        def _prune():
            bnd = bound
            if inbucket:
                # within-row-block threshold refresh: the k-th largest
                # suffix-norm LOWER bound among alive candidates prunes
                # blocks before any of them reaches a shortlist merge
                # (see ops/pallas_ivf._ivf_pruned_kernel for the math
                # and the self-prune impossibility argument)
                if ascending:
                    tail = jnp.sqrt(qtail) + jnp.sqrt(xtail)
                    lb = -(partial + tail * tail)
                else:
                    lb = cum[:] - jnp.sqrt(qtail * xtail)
                lb = lb - 1e-5 * jnp.abs(lb) - 1e-6   # f32 safety shave
                lb = jnp.where(alive[:] > 0.5, lb, NEG_INF)
                gidx = jax.lax.broadcasted_iota(
                    jnp.int32, lb.shape, 1
                )
                lb_k, _ = _select_topk(lb, gidx, k)
                bnd = jnp.maximum(bnd, lb_k[:, k - 1:k])
            alive[:] = jnp.where(ub < bnd, 0.0, alive[:])

        @pl.when(jb == nblk - 1)
        def _merge():
            scores = jnp.where(alive[:] > 0.5, final, NEG_INF)
            gidx = (
                jax.lax.broadcasted_iota(jnp.int32, (b, block), 1)
                + j * block
            )
            blk_v, blk_i = _select_topk(scores, gidx, k)
            cat_v = jnp.concatenate([best_v[:], blk_v], axis=1)
            cat_i = jnp.concatenate([best_i[:], blk_i], axis=1)
            new_v, new_i = _select_topk(cat_v, cat_i, k)
            best_v[:] = new_v
            best_i[:] = new_i

    @pl.when((j == pl.num_programs(0) - 1) & (jb == nblk - 1))
    def _finish():
        fv = best_v[:]
        out_v_ref[:] = fv
        out_i_ref[:] = jnp.where(jnp.isneginf(fv), -1, best_i[:])


@sentinel_jit("ops.pallas.pruned_fused_topk",
              static_argnames=("k", "block", "dim_block", "check_every",
                               "ascending", "interpret", "sq", "inbucket"))
def pruned_fused_topk(
    q: jax.Array,              # [b, d] f32
    x_blk: jax.Array,          # [nblk, n, dblk] rows (f32/bf16) or codes
    bsq_blk: jax.Array,        # [nblk, n] f32 per-block (decoded) norms
    x_sqnorm: jax.Array,       # [n] f32 total (decoded) norms
    valid: jax.Array,          # [n] bool/float
    sq_vmin,                   # [d] f32 codec params (None for float rows)
    sq_scale,
    k: int,
    block: int = 2048,
    dim_block: int = 128,
    check_every: int = 1,
    ascending: bool = True,
    interpret: bool = False,
    sq: bool = False,
    inbucket: bool = True,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Early-pruning streaming search over the dimension-blocked store
    mirror (slot_store.vecs_blk/bsq_blk) -> (scores[b,k], slots[b,k],
    stats[b,4]). Same contract as fused_topk plus the pruning stats."""
    b, d = q.shape
    nblk, n, dblk = x_blk.shape
    assert n % block == 0, f"n={n} not a multiple of block={block}"
    assert dblk * nblk == d, f"blocked dim {nblk}x{dblk} != {d}"
    q32 = q.astype(jnp.float32)
    qsq = jnp.einsum("bd,bd->b", q32, q32,
                     precision=jax.lax.Precision.HIGHEST)[:, None]
    from dingo_tpu.ops.blocked import query_prefix_sqnorms

    qpsq = query_prefix_sqnorms(q32, dblk)                 # [b, nblk]
    grid = (n // block, nblk)
    in_specs = [
        pl.BlockSpec((b, dblk), lambda j, jb: (0, jb)),     # q (dim block)
        pl.BlockSpec((b, 1), lambda j, jb: (0, 0)),         # qsq
        pl.BlockSpec((b, 1), lambda j, jb: (0, jb)),        # qpsq prefix
        pl.BlockSpec((1, block, dblk), lambda j, jb: (jb, j, 0)),   # x tile
        pl.BlockSpec((1, 1, block), lambda j, jb: (jb, 0, j)),      # bsq
        pl.BlockSpec((1, block), lambda j, jb: (0, j)),     # xsq total
        pl.BlockSpec((1, block), lambda j, jb: (0, j)),     # valid
    ]
    args = [
        q32, qsq, qpsq, x_blk, bsq_blk[:, None, :],
        x_sqnorm[None, :], valid.astype(jnp.float32)[None, :],
    ]
    if sq:
        in_specs += [
            pl.BlockSpec((1, dblk), lambda j, jb: (0, jb)),
            pl.BlockSpec((1, dblk), lambda j, jb: (0, jb)),
        ]
        args += [sq_vmin[None, :], sq_scale[None, :]]
    out_v, out_i, out_s = pl.pallas_call(
        functools.partial(
            _pruned_fused_kernel, k=k, block=block, nblk=nblk,
            check_every=check_every, ascending=ascending, sq=sq,
            inbucket=inbucket,
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((b, k), lambda j, jb: (0, 0)),
            pl.BlockSpec((b, k), lambda j, jb: (0, 0)),
            pl.BlockSpec((b, STATS_PAD), lambda j, jb: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, k), jnp.float32),
            jax.ShapeDtypeStruct((b, k), jnp.int32),
            jax.ShapeDtypeStruct((b, STATS_PAD), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((b, k), jnp.float32),       # best_v
            pltpu.VMEM((b, k), jnp.int32),         # best_i
            pltpu.VMEM((b, block), jnp.float32),   # cum dot
            pltpu.VMEM((b, block), jnp.float32),   # alive mask
            pltpu.VMEM((1, block), jnp.float32),   # x per-block prefixes
        ],
        interpret=interpret,
    )(*args)
    return out_v, out_i, out_s[:, :4]


def pruned_fused_search(
    q,
    x_blk: jax.Array,
    bsq_blk: jax.Array,
    x_sqnorm: jax.Array,
    valid: jax.Array,
    k: int,
    block: int = 2048,
    ascending: bool = True,
    sq_vmin=None,
    sq_scale=None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Host-friendly wrapper over the blocked store mirror. The mirror's
    capacity is pow2 >= 4096, so `block` is clamped down to divide it
    exactly (no padding copy of a [nblk, n, dblk] array on the hot path)."""
    from dingo_tpu.common.config import FLAGS

    n = x_blk.shape[1]
    block = min(block, n)
    interpret = jax.default_backend() not in ("tpu", "axon")
    check = max(1, int(FLAGS.get("ivf_prune_check_interval")))
    return pruned_fused_topk(
        jnp.asarray(q), x_blk, bsq_blk, x_sqnorm, valid,
        sq_vmin, sq_scale,
        k=k, block=block, dim_block=int(x_blk.shape[2]), check_every=check,
        ascending=ascending, interpret=interpret, sq=sq_vmin is not None,
        inbucket=bool(FLAGS.get("ivf_prune_inbucket_bound")),
    )
