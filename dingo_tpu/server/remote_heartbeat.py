"""Store -> remote coordinator heartbeat over grpc.

The in-process path calls CoordinatorControl directly (StoreNode.heartbeat_
once); multi-process stores use this grpc client instead — same payload,
same command execution on the response (store/heartbeat.cc:61,294 flow).
"""

from __future__ import annotations

import grpc

from dingo_tpu.coordinator.control import RegionCmd, RegionCmdType
from dingo_tpu.server import convert, pb
from dingo_tpu.server.rpc import ServiceStub


class RemoteHeartbeat:
    def __init__(self, node, coordinator_addr: str):
        self.node = node
        self._channel = grpc.insecure_channel(coordinator_addr)
        self._stub = ServiceStub(self._channel, "CoordinatorService")

    def beat(self) -> int:
        regions = self.node.meta.get_all_regions()
        leader_ids = [
            r.id for r in regions
            if (n := self.node.engine.get_node(r.id)) is not None
            and n.is_leader()
        ]
        req = pb.StoreHeartbeatRequest()
        req.store_id = self.node.store_id
        req.region_ids.extend(r.id for r in regions)
        req.leader_region_ids.extend(leader_ids)
        for r in regions:
            if r.id in leader_ids:
                req.region_definitions.add().CopyFrom(
                    convert.region_def_to_pb(r.definition)
                )
        resp = self._stub.StoreHeartbeat(req)
        executed = 0
        for c in resp.commands:
            cmd = convert.region_cmd_from_pb(c)
            try:
                self.node.execute_region_cmd(cmd)
                executed += 1
            except Exception as e:  # noqa: BLE001
                from dingo_tpu.raft.core import NotLeader

                if isinstance(e, NotLeader) and e.leader_hint:
                    # hand the command back to the coordinator addressed at
                    # the hinted leader (same flow as the in-process path)
                    rq = pb.RequeueRegionCmdRequest()
                    rq.cmd.CopyFrom(c)
                    rq.target_store_id = e.leader_hint.split("/")[0]
                    rq.from_store_id = self.node.store_id
                    try:
                        self._stub.RequeueRegionCmd(rq)
                    except Exception:
                        pass
        return executed
