"""DocumentIndex: BM25 inverted index with typed columns + range queries.

Reference: src/document/document_index.h wraps tantivy (tokenized text
fields + i64/f64/bytes/bool columns; queries are boolean text matches with
optional column constraints, parsed from tantivy query syntax). This is an
original implementation covering that surface: tokenization, positional
postings with term frequencies, BM25 ranking, AND/OR boolean modes, PHRASE
queries (consecutive positions), field-restricted terms, typed column
schema with validation, sorted column indexes serving range queries, a
query parser (document/query.py), delete/upsert, save/load.
"""

from __future__ import annotations

import bisect
import json
import math
import os
import re
import threading
from collections import defaultdict

from dingo_tpu.common import persist
from typing import Any, Dict, List, Optional, Sequence, Tuple

_TOKEN_RE = re.compile(r"[a-z0-9]+")

FIELD_POSITION_GAP = 1_000_000
BM25_K1 = 1.2
BM25_B = 0.75

#: column types (tantivy schema field kinds we cover)
COLUMN_TYPES = ("text", "i64", "f64", "bytes", "bool")


def tokenize(text: str) -> List[str]:
    return _TOKEN_RE.findall(text.lower())


class SchemaError(ValueError):
    pass


def _check_typed(field: str, ftype: str, value: Any) -> Any:
    """Validate/coerce one column value against its schema type."""
    if ftype == "text":
        if not isinstance(value, str):
            raise SchemaError(f"{field}: expected text, got {type(value)}")
        return value
    if ftype == "i64":
        if isinstance(value, bool) or not isinstance(value, int):
            raise SchemaError(f"{field}: expected i64, got {value!r}")
        return value
    if ftype == "f64":
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise SchemaError(f"{field}: expected f64, got {value!r}")
        return float(value)
    if ftype == "bytes":
        if not isinstance(value, (bytes, bytearray)):
            raise SchemaError(f"{field}: expected bytes, got {value!r}")
        return bytes(value)
    if ftype == "bool":
        if not isinstance(value, bool):
            raise SchemaError(f"{field}: expected bool, got {value!r}")
        return value
    raise SchemaError(f"{field}: unknown column type {ftype!r}")


class DocumentIndex:
    def __init__(self, index_id: int, text_fields: Sequence[str] = ("text",),
                 schema: Optional[Dict[str, str]] = None):
        """schema: column name -> type in COLUMN_TYPES. Text-typed schema
        columns are indexed alongside `text_fields`; typed columns are
        validated on add and back the range/eq predicates. schema=None =
        schemaless (everything accepted, filters compare raw values)."""
        self.id = index_id
        self.text_fields = list(text_fields)
        self.schema = dict(schema) if schema else None
        if self.schema:
            for f, t in self.schema.items():
                if t not in COLUMN_TYPES:
                    raise SchemaError(f"{f}: unknown column type {t!r}")
            for f, t in self.schema.items():
                if t == "text" and f not in self.text_fields:
                    self.text_fields.append(f)
        self._lock = threading.RLock()
        #: term -> {doc_id: [positions]} (tf == len(positions))
        self._postings: Dict[str, Dict[int, List[int]]] = defaultdict(dict)
        #: doc_id -> (doc dict, token_count)
        self._docs: Dict[int, Tuple[Dict[str, Any], int]] = {}
        #: doc_id -> {text_field: (pos_start, pos_end)} for field-restricted
        #: terms (recomputed on load — derived from the doc text)
        self._field_spans: Dict[int, Dict[str, Tuple[int, int]]] = {}
        #: typed column -> sorted [(value, doc_id)] (lazy; None = dirty)
        self._column_sorted: Dict[str, Optional[list]] = {}
        self._total_tokens = 0
        self.apply_log_id = 0

    # ---------------- mutation ----------------
    def check_doc(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        """Validate/coerce a doc against the schema (raises SchemaError).
        Service handlers call this BEFORE proposing through raft so an
        invalid doc never enters the log."""
        if not self.schema:
            return doc
        return {
            k: (_check_typed(k, self.schema[k], v)
                if k in self.schema else v)
            for k, v in doc.items()
        }

    def add(self, doc_id: int, doc: Dict[str, Any]) -> None:
        doc = self.check_doc(doc)
        with self._lock:
            if doc_id in self._docs:
                self._remove_unlocked(doc_id)
            ntok = 0
            pos = 0
            spans: Dict[str, Tuple[int, int]] = {}
            for field in self.text_fields:
                value = doc.get(field)
                if not isinstance(value, str):
                    continue
                start = pos
                for tok in tokenize(value):
                    self._postings[tok].setdefault(doc_id, []).append(pos)
                    pos += 1
                    ntok += 1
                spans[field] = (start, pos)
                # position gap between fields so a phrase cannot match
                # across a field boundary (tantivy parity)
                pos += FIELD_POSITION_GAP
            self._docs[doc_id] = (dict(doc), ntok)
            self._field_spans[doc_id] = spans
            self._total_tokens += ntok
            self._dirty_columns(doc)

    upsert = add

    def delete(self, doc_ids: Sequence[int]) -> int:
        with self._lock:
            n = 0
            for did in doc_ids:
                if did in self._docs:
                    self._remove_unlocked(int(did))
                    n += 1
            return n

    def _remove_unlocked(self, doc_id: int) -> None:
        doc, ntok = self._docs.pop(doc_id)
        self._field_spans.pop(doc_id, None)
        self._total_tokens -= ntok
        for field in self.text_fields:
            value = doc.get(field)
            if isinstance(value, str):
                for tok in set(tokenize(value)):
                    entry = self._postings.get(tok)
                    if entry is not None:
                        entry.pop(doc_id, None)
                        if not entry:
                            del self._postings[tok]
        self._dirty_columns(doc)

    def _dirty_columns(self, doc: Dict[str, Any]) -> None:
        if not self.schema:
            return
        for f, t in self.schema.items():
            if t in ("i64", "f64", "bytes") and f in doc:
                self._column_sorted[f] = None

    # ---------------- typed column index ------------------------------------
    def _sorted_column(self, field: str) -> Tuple[list, list]:
        """(sorted values, doc_ids aligned) for a typed column — cached
        together so bisect lookups stay O(log n) after the one-time build
        (lazy rebuild on mutation)."""
        cached = self._column_sorted.get(field)
        if cached is not None:
            return cached
        pairs = []
        for did, (doc, _n) in self._docs.items():
            v = doc.get(field)
            if v is not None:
                pairs.append((v, did))
        pairs.sort()
        cached = ([p[0] for p in pairs], [p[1] for p in pairs])
        self._column_sorted[field] = cached
        return cached

    def range_select(self, field: str, lo=None, hi=None,
                     incl_lo: bool = True, incl_hi: bool = True) -> List[int]:
        """Doc ids whose column lies in the range. Schema-typed columns
        ride the sorted column index (bisect); schemaless columns fall
        back to a per-doc scan with safe comparisons (mixed value types
        cannot sort, and nothing invalidates a schemaless cache)."""
        with self._lock:
            ftype = self.schema.get(field) if self.schema else None
            if self.schema and ftype not in ("i64", "f64", "bytes"):
                raise SchemaError(f"{field}: not a range-indexable column")
            if ftype is None:
                out = []
                for did, (doc, _n) in self._docs.items():
                    v = doc.get(field)
                    if v is None:
                        continue
                    try:
                        if lo is not None and (
                            v < lo or (not incl_lo and v == lo)
                        ):
                            continue
                        if hi is not None and (
                            v > hi or (not incl_hi and v == hi)
                        ):
                            continue
                    except TypeError:
                        continue
                    out.append(did)
                return sorted(out)
            values, doc_ids = self._sorted_column(field)
            i = 0
            if lo is not None:
                i = (bisect.bisect_left(values, lo) if incl_lo
                     else bisect.bisect_right(values, lo))
            j = len(values)
            if hi is not None:
                j = (bisect.bisect_right(values, hi) if incl_hi
                     else bisect.bisect_left(values, hi))
            return sorted(doc_ids[i:j])

    # ---------------- search ----------------
    def search(
        self,
        query: str,
        topk: int = 10,
        mode: str = "or",
        column_filter: Optional[Dict[str, Any]] = None,
    ) -> List[Tuple[int, float]]:
        """BM25-ranked (doc_id, score), best first.
        mode: 'or' | 'and' | 'phrase' (terms at consecutive positions)
        | 'query' (full parser syntax — document/query.py)."""
        if mode == "query":
            from dingo_tpu.document.query import parse_query

            return self.search_query(
                parse_query(query, self.schema), topk,
                column_filter=column_filter,
            )
        terms = tokenize(query)
        if not terms:
            return []
        with self._lock:
            scores = self._bm25_unlocked(terms)
            hits = scores.items()
            if mode == "phrase":
                hits = [
                    (did, sc) for did, sc in scores.items()
                    if self._phrase_match_unlocked(did, terms)
                ]
            elif mode == "and":
                need = len(set(terms))
                uniq_matched: Dict[int, set] = defaultdict(set)
                for term in set(terms):
                    for did in self._postings.get(term, {}):
                        uniq_matched[did].add(term)
                hits = [
                    (did, sc) for did, sc in scores.items()
                    if len(uniq_matched.get(did, ())) >= need
                ]
            if column_filter:
                hits = [
                    (did, sc) for did, sc in hits
                    if all(self._docs[did][0].get(k) == v
                           for k, v in column_filter.items())
                ]
            return sorted(hits, key=lambda t: (-t[1], t[0]))[:topk]

    def search_query(self, pq, topk: int = 10,
                     column_filter: Optional[Dict[str, Any]] = None
                     ) -> List[Tuple[int, float]]:
        """Evaluate a ParsedQuery (document/query.py): scored text terms,
        +required/-excluded, phrases, field-restricted terms, and typed
        column predicates (ranges ride the sorted column index)."""
        with self._lock:
            if pq.terms:
                scores = self._bm25_unlocked(pq.terms)
                if pq.mode == "and":
                    need = set(pq.terms)
                    scores = {
                        did: sc for did, sc in scores.items()
                        if all(did in self._postings.get(t, {})
                               for t in need)
                    }
            elif pq.predicates:
                # pure column query: candidates from the POSITIVE
                # predicates' column indexes (negated ones cannot generate
                # candidates and filter below; all-negative queries
                # evaluate against every doc, like tantivy's all-query)
                cand: Optional[set] = None
                for p in pq.predicates:
                    if p.negate:
                        continue
                    if p.op == "range":
                        ids = set(self.range_select(
                            p.field, p.lo, p.hi, p.incl_lo, p.incl_hi))
                    else:
                        ids = {
                            did for did, (doc, _n) in self._docs.items()
                            if doc.get(p.field) == p.value
                        }
                    cand = ids if cand is None else (cand & ids)
                if cand is None:
                    cand = set(self._docs)
                scores = {did: 1.0 for did in cand}
                for p in pq.predicates:
                    if p.negate:
                        scores = {
                            d: s for d, s in scores.items()
                            if p.matches(self._docs[d][0])
                        }
            else:
                return []
            for t in pq.required:
                post = self._postings.get(t, {})
                scores = {d: s for d, s in scores.items() if d in post}
            for t in pq.excluded:
                post = self._postings.get(t, {})
                scores = {d: s for d, s in scores.items() if d not in post}
            for phrase in pq.phrases:
                scores = {
                    d: s for d, s in scores.items()
                    if self._phrase_match_unlocked(d, phrase)
                }
            for phrase in getattr(pq, "neg_phrases", ()):
                scores = {
                    d: s for d, s in scores.items()
                    if not self._phrase_match_unlocked(d, phrase)
                }
            for field, term in pq.field_terms:
                scores = {
                    d: s for d, s in scores.items()
                    if self._term_in_field_unlocked(d, field, term)
                }
            if pq.terms and pq.predicates:
                for p in pq.predicates:
                    scores = {
                        d: s for d, s in scores.items()
                        if p.matches(self._docs[d][0])
                    }
            if column_filter:
                scores = {
                    d: s for d, s in scores.items()
                    if all(self._docs[d][0].get(k) == v
                           for k, v in column_filter.items())
                }
            return sorted(
                scores.items(), key=lambda t: (-t[1], t[0])
            )[:topk]

    def _bm25_unlocked(self, terms: List[str]) -> Dict[int, float]:
        n_docs = len(self._docs)
        if n_docs == 0:
            return {}
        avg_len = self._total_tokens / n_docs
        scores: Dict[int, float] = defaultdict(float)
        for term in terms:
            postings = self._postings.get(term)
            if not postings:
                continue
            idf = math.log(1 + (n_docs - len(postings) + 0.5)
                           / (len(postings) + 0.5))
            for did, positions in postings.items():
                tf = len(positions)
                dlen = self._docs[did][1] or 1
                denom = tf + BM25_K1 * (
                    1 - BM25_B + BM25_B * dlen / max(avg_len, 1e-9)
                )
                scores[did] += idf * tf * (BM25_K1 + 1) / denom
        return scores

    def _phrase_match_unlocked(self, doc_id: int,
                               terms: List[str]) -> bool:
        """True when the terms occur at consecutive positions in order."""
        lists = []
        for term in terms:
            positions = self._postings.get(term, {}).get(doc_id)
            if not positions:
                return False
            lists.append(set(positions))
        return any(
            all(start + i in lists[i] for i in range(1, len(lists)))
            for start in lists[0]
        )

    def _term_in_field_unlocked(self, doc_id: int, field: str,
                                term: str) -> bool:
        span = self._field_spans.get(doc_id, {}).get(field)
        if span is None:
            return False
        positions = self._postings.get(term, {}).get(doc_id)
        if not positions:
            return False
        lo, hi = span
        return any(lo <= p < hi for p in positions)

    def get(self, doc_id: int) -> Optional[Dict[str, Any]]:
        with self._lock:
            entry = self._docs.get(doc_id)
            return entry[0] if entry else None

    def count(self) -> int:
        with self._lock:
            return len(self._docs)

    # ---------------- persistence ----------------
    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        with self._lock:
            blob = persist.dumps({
                "postings": dict(self._postings),
                "docs": self._docs,
                "total_tokens": self._total_tokens,
            })
        with open(os.path.join(path, "document.idx"), "wb") as f:
            f.write(blob)
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump({
                "text_fields": self.text_fields,
                "apply_log_id": self.apply_log_id,
                "schema": self.schema,
            }, f)

    def load(self, path: str) -> None:
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        with open(os.path.join(path, "document.idx"), "rb") as f:
            state = persist.loads(f.read())
        with self._lock:
            self.text_fields = meta["text_fields"]
            self.apply_log_id = meta["apply_log_id"]
            self.schema = meta.get("schema")
            postings = state["postings"]
            # migrate pre-positional snapshots ({doc: tf} ints): synthesize
            # positions so BM25 keeps working; phrase matches degrade to
            # position-0 runs until the doc is re-upserted
            for term, docs in postings.items():
                for did, val in list(docs.items()):
                    if isinstance(val, int):
                        docs[did] = list(range(val))
            self._postings = defaultdict(dict, postings)
            self._docs = state["docs"]
            self._total_tokens = state["total_tokens"]
            # field spans + column indexes are derived state: recompute
            # spans from the stored docs (same deterministic walk as add)
            self._field_spans = {}
            self._column_sorted = {}
            for did, (doc, _n) in self._docs.items():
                pos = 0
                spans: Dict[str, Tuple[int, int]] = {}
                for field in self.text_fields:
                    value = doc.get(field)
                    if not isinstance(value, str):
                        continue
                    start = pos
                    pos += len(tokenize(value))
                    spans[field] = (start, pos)
                    pos += FIELD_POSITION_GAP
                self._field_spans[did] = spans
