"""Stall-free serving pipeline (common/pipeline.py + the coalescer's
overlapped-dispatch arm).

The pipeline is only allowed to change WHEN work happens, never what
comes back: the tentpole assertions here are byte-identical results
against the serial path for every index family x precision tier, zero
steady-state recompiles across the staging-depth ladder, and the
dispatch/resolve split actually overlapping (region B dispatches before
region A resolves). The shutdown contract extends to the completion
lane: drain resolves, no-drain abandons but still runs the fetch.
"""

import threading
import time

import numpy as np
import pytest

from dingo_tpu.common.config import FLAGS
from dingo_tpu.common.coalescer import CoalescerStopped, SearchCoalescer
from dingo_tpu.common.metrics import METRICS
from dingo_tpu.common.pipeline import (
    CompletionLane,
    StagedBatch,
    StagingRing,
    _next_pow2,
)
from dingo_tpu.index.base import IndexParameter, IndexType, Metric
from dingo_tpu.index.flat import TpuFlat
from dingo_tpu.index.hnsw import TpuHnsw
from dingo_tpu.index.ivf_flat import TpuIvfFlat
from dingo_tpu.index.ivf_pq import TpuIvfPq

N, D, K = 2000, 32, 10


@pytest.fixture
def pipeline_flags():
    """Force the pipeline on (the tri-state default is TPU-only) and
    restore every knob the tests twist."""
    FLAGS.set("pipeline_enabled", "true")
    yield
    FLAGS.set("pipeline_enabled", "auto")
    FLAGS.set("pipeline_depth", 2)
    FLAGS.set("hnsw_device_search", "auto")


@pytest.fixture(scope="module")
def corpus():
    rng = np.random.default_rng(7)
    x = rng.standard_normal((N, D)).astype(np.float32)
    ids = np.arange(N, dtype=np.int64)
    q = x[:16] + 0.01 * rng.standard_normal((16, D)).astype(np.float32)
    return ids, x, q


def _build(family, precision, corpus, idx_id=1):
    ids, x, _ = corpus
    if family == "flat":
        idx = TpuFlat(idx_id, IndexParameter(
            index_type=IndexType.FLAT, dimension=D, precision=precision))
        idx.add(ids, x)
    elif family == "ivf_flat":
        idx = TpuIvfFlat(idx_id, IndexParameter(
            index_type=IndexType.IVF_FLAT, dimension=D, ncentroids=16,
            default_nprobe=16, precision=precision))
        idx.add(ids, x)
        idx.train()
    elif family == "ivf_pq":
        idx = TpuIvfPq(idx_id, IndexParameter(
            index_type=IndexType.IVF_PQ, dimension=D, ncentroids=16,
            default_nprobe=16, nsubvector=8))
        idx.add(ids, x)
        idx.train()
    elif family == "hnsw":
        idx = TpuHnsw(idx_id, IndexParameter(
            index_type=IndexType.HNSW, dimension=D, nlinks=16,
            efconstruction=80, precision=precision))
        idx.add(ids, x)
        FLAGS.set("hnsw_device_search", True)
    else:  # pragma: no cover
        raise AssertionError(family)
    return idx


def _via_coalescer(idx, q, chunks=4):
    """Submit q in `chunks`-row batches under DISTINCT keys (so batch
    composition is identical between the serial and pipelined arms) and
    return the flattened per-query rows."""
    def run(key, stacked):
        return idx.search(stacked, K)

    def dispatch(key, stacked, staged=None):
        return idx.search_async(stacked, K, staged=staged)

    co = SearchCoalescer(run, window_ms=5.0, dispatch_fn=dispatch)
    try:
        futs = [co.submit(i, q[i:i + chunks])
                for i in range(0, len(q), chunks)]
        return [r for f in futs for r in f.result(timeout=60)]
    finally:
        co.stop()


def _assert_bitwise_equal(got, want):
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert np.array_equal(np.asarray(g.ids), np.asarray(w.ids))
        assert np.asarray(g.distances, np.float32).tobytes() == \
            np.asarray(w.distances, np.float32).tobytes()


# ---------------- byte-identical across families x tiers ----------------

_FAMILIES = [
    ("flat", "fp32"), ("flat", "bf16"), ("flat", "sq8"),
    ("ivf_flat", "fp32"), ("ivf_flat", "bf16"), ("ivf_flat", "sq8"),
    ("ivf_pq", "fp32"),
    ("hnsw", "fp32"), ("hnsw", "bf16"), ("hnsw", "sq8"),
]


@pytest.mark.parametrize("family,precision", _FAMILIES)
def test_pipelined_byte_identical(pipeline_flags, corpus, family,
                                  precision):
    """The pipelined path (overlapped dispatch + staged upload + lane
    resolve) returns bit-equal ids AND distances vs the serial coalescer
    arm and vs a direct per-chunk search."""
    _, _, q = corpus
    idx = _build(family, precision, corpus)
    direct = [r for i in range(0, len(q), 4)
              for r in idx.search(q[i:i + 4], K)]
    FLAGS.set("pipeline_enabled", "false")
    serial = _via_coalescer(idx, q)
    FLAGS.set("pipeline_enabled", "true")
    pipelined = _via_coalescer(idx, q)
    _assert_bitwise_equal(serial, direct)
    _assert_bitwise_equal(pipelined, direct)


def test_depth_ladder_no_recompiles_and_identical(pipeline_flags, corpus):
    """Once warm at depth 1, running the same shapes at depth 2 and 4
    never retraces (the staging ring pads on the same pow2 ladder as
    _pad_batch) and returns the same bytes."""
    _, _, q = corpus
    idx = _build("flat", "fp32", corpus)
    baseline = None
    rc = METRICS.counter("xla.recompiles")
    for depth in (1, 2, 4):
        FLAGS.set("pipeline_depth", depth)
        if depth > 1:
            rc0 = rc.get()
        rows = _via_coalescer(idx, q)
        if baseline is None:
            baseline = rows
        else:
            assert rc.get() - rc0 == 0, f"depth {depth} retraced"
            _assert_bitwise_equal(rows, baseline)


# ---------------- dispatch/resolve overlap ------------------------------

def test_dispatch_overlap_ordering(pipeline_flags):
    """Both due batches dispatch before EITHER resolves: region B's
    kernel is enqueued while region A's fetch is still pending on the
    completion lane."""
    events = []
    guard = threading.Lock()

    def run(key, stacked):  # pragma: no cover — pipelined arm only
        raise AssertionError("serial arm must not run")

    def dispatch(key, stacked, staged=None):
        with guard:
            events.append(("dispatch", key))

        def thunk():
            with guard:
                events.append(("resolve", key))
            return [key] * len(stacked)

        return thunk

    co = SearchCoalescer(run, window_ms=50.0, dispatch_fn=dispatch)
    try:
        fa = co.submit("a", np.zeros((2, 4), np.float32))
        fb = co.submit("b", np.zeros((2, 4), np.float32))
        assert fa.result(timeout=10) == ["a", "a"]
        assert fb.result(timeout=10) == ["b", "b"]
    finally:
        co.stop()
    order = {e: i for i, e in enumerate(events)}
    assert order[("dispatch", "a")] < order[("resolve", "a")]
    assert order[("dispatch", "b")] < order[("resolve", "a")], events
    # FIFO lane: resolves happen in dispatch order
    assert order[("resolve", "a")] < order[("resolve", "b")]


def test_stage_totals_record_pipeline_stages(pipeline_flags):
    def dispatch(key, stacked, staged=None):
        return lambda: list(range(len(stacked)))

    co = SearchCoalescer(lambda k, s: list(range(len(s))),
                         window_ms=5.0, dispatch_fn=dispatch)
    try:
        co.submit("k", np.zeros((2, 4), np.float32)).result(timeout=10)
        deadline = time.monotonic() + 5
        while "resolve" not in co.stage_totals() \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        totals = co.stage_totals()
    finally:
        co.stop()
    assert "dispatch" in totals and "resolve" in totals, totals


# ---------------- shutdown contract on the lane -------------------------

def test_stop_drain_resolves_queued_handoffs(pipeline_flags):
    """stop(drain=True) while a handoff is mid-resolve and another is
    queued: every future still gets its real results."""
    release = threading.Event()

    def dispatch(key, stacked, staged=None):
        def thunk():
            if key == "a":
                release.wait(timeout=10)
            return [key] * len(stacked)
        return thunk

    co = SearchCoalescer(lambda k, s: [k] * len(s), window_ms=5.0,
                         dispatch_fn=dispatch)
    fa = co.submit("a", np.zeros((1, 4), np.float32))
    fb = co.submit("b", np.zeros((1, 4), np.float32))
    threading.Timer(0.3, release.set).start()
    co.stop(drain=True)
    assert fa.result(timeout=10) == ["a"]
    assert fb.result(timeout=10) == ["b"]


def test_stop_nodrain_abandons_but_runs_fetch(pipeline_flags):
    """stop(drain=False): queued handoffs fail fast with
    CoalescerStopped, but their thunk still runs (device-side leases
    must release)."""
    release = threading.Event()
    ran = []

    def dispatch(key, stacked, staged=None):
        def thunk():
            if key == "a":
                release.wait(timeout=10)
            ran.append(key)
            return [key] * len(stacked)
        return thunk

    co = SearchCoalescer(lambda k, s: [k] * len(s), window_ms=5.0,
                         dispatch_fn=dispatch)
    fa = co.submit("a", np.zeros((1, 4), np.float32))
    fb = co.submit("b", np.zeros((1, 4), np.float32))
    # wait until a is mid-resolve on the lane (b queued behind it)
    deadline = time.monotonic() + 5
    while co._lane.depth() < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    threading.Timer(0.3, release.set).start()
    co.stop(drain=False)
    assert fa.result(timeout=10) == ["a"]        # mid-resolve completes
    with pytest.raises(CoalescerStopped):
        fb.result(timeout=10)
    assert "b" in ran                            # fetch ran anyway


# ---------------- staging ring primitives -------------------------------

def test_staging_ring_pads_on_ladder_and_zero_tail():
    ring = StagingRing(depth=2)
    stacked = np.arange(5 * 4, dtype=np.float32).reshape(5, 4)
    staged = ring.stage(stacked)
    assert staged.rows == 5
    qpad = staged.take(stacked)
    assert qpad is not None
    assert qpad.shape == (_next_pow2(5), 4) == (8, 4)
    host = np.asarray(qpad)
    assert np.array_equal(host[:5], stacked)
    assert not host[5:].any()
    staged.release()


def test_staged_batch_take_identity():
    ring = StagingRing(depth=1)
    stacked = np.ones((2, 4), np.float32)
    staged = ring.stage(stacked)
    # the exact staged array claims the upload; a copy (what a dtype
    # rebind in _prep_queries produces) must NOT
    assert staged.take(stacked) is not None
    assert staged.take(stacked.copy()) is None
    assert staged.take(np.asarray(stacked, np.float64)) is None
    staged.release()
    staged.release()  # idempotent


def test_staging_ring_depth_backpressure():
    ring = StagingRing(depth=2)
    a = ring.stage(np.zeros((1, 4), np.float32))
    b = ring.stage(np.zeros((1, 4), np.float32))
    third_in = threading.Event()

    def third():
        s = ring.stage(np.zeros((1, 4), np.float32))
        third_in.set()
        s.release()

    t = threading.Thread(target=third, daemon=True)
    t.start()
    assert not third_in.wait(timeout=0.3)   # both slots leased: blocked
    a.release()
    assert third_in.wait(timeout=5)         # release unblocks the ring
    b.release()
    t.join(timeout=5)


def test_staging_ring_closed_raises():
    ring = StagingRing(depth=1)
    ring.close()
    with pytest.raises(RuntimeError, match="closed"):
        ring.stage(np.zeros((1, 4), np.float32))


def test_completion_lane_fifo_and_stop_idempotent():
    done = []

    class H:
        def __init__(self, tag):
            self.tag = tag

        def resolve(self):
            done.append(self.tag)

        def abandon(self):  # pragma: no cover
            done.append(("abandon", self.tag))

    lane = CompletionLane(name="test-lane")
    for i in range(5):
        assert lane.submit(H(i))
    lane.stop(drain=True)
    assert done == [0, 1, 2, 3, 4]
    assert not lane.submit(H(9))    # stopped lane refuses new handoffs
    lane.stop(drain=True)           # idempotent
