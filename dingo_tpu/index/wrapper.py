"""VectorIndexWrapper: lifecycle state machine around a VectorIndex.

Reference: src/vector/vector_index.h:283-506 — tracks ready/stop/build-error
flags, apply_log_id & snapshot_log_id (:467-470), own/share/sibling index
pointers used during region split & merge (:476-480), pending-task counters,
and the save threshold by write count (:497-500). The raft apply handlers
talk to the wrapper, never to the index directly (§3.2 dual-write contract:
RocksDB is the source of truth; the in-memory index is an apply-log-tracked
materialized view).
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

import numpy as np


from dingo_tpu.index.base import (
    FilterSpec,
    IndexParameter,
    SearchResult,
    VectorIndex,
    VectorIndexError,
)
from dingo_tpu.index.factory import new_index
from dingo_tpu.ops.distance import metric_ascending


def _merge_results(a: SearchResult, b: SearchResult, topk: int, metric):
    ids = np.concatenate([a.ids, b.ids])
    d = np.concatenate([a.distances, b.distances])
    order = np.argsort(d if metric_ascending(metric) else -d)[:topk]
    return SearchResult(ids[order], d[order])


class VectorIndexWrapper:
    def __init__(self, index_id: int, parameter: IndexParameter,
                 save_write_threshold: int = 10000):
        self.id = index_id
        self.parameter = parameter
        self._lock = threading.RLock()
        self.own_index: Optional[VectorIndex] = None
        #: parent's index served by a child region after split until its own
        #: rebuild completes (SplitHandler SetShareVectorIndex,
        #: raft_apply_handler.cc:372,630)
        self.share_index: Optional["VectorIndexWrapper"] = None
        #: pre-merge sibling's index (raft_apply_handler.cc:1021)
        self.sibling_index: Optional["VectorIndexWrapper"] = None
        self.ready = False
        self.stopped = False
        self.build_error = False
        self.is_switching = False
        self.apply_log_id = 0
        self.snapshot_log_id = 0
        self.pending_tasks = 0
        self.write_count = 0
        self.save_write_threshold = save_write_threshold

    # -- index lifecycle -----------------------------------------------------
    def build_own(self) -> VectorIndex:
        with self._lock:
            self.own_index = new_index(self.id, self.parameter)
            return self.own_index

    def set_own(self, index: VectorIndex) -> None:
        """Atomic switch after rebuild/catch-up (UpdateVectorIndex,
        vector_index_manager.cc:1149 'final round under switching flag')."""
        with self._lock:
            self.own_index = index
            self.apply_log_id = index.apply_log_id
            self.ready = True
            self.build_error = False

    def set_share(self, share: Optional["VectorIndexWrapper"]) -> None:
        with self._lock:
            self.share_index = share

    def set_sibling(self, sibling: Optional["VectorIndexWrapper"]) -> None:
        with self._lock:
            self.sibling_index = sibling

    def active(self) -> Optional[VectorIndex]:
        """Index to serve searches from: own if ready, else shared parent's
        (split children serve the parent's index filtered to their range)."""
        with self._lock:
            if self.ready and self.own_index is not None:
                return self.own_index
            if self.share_index is not None:
                return self.share_index.active()
            return None

    def is_ready(self) -> bool:
        with self._lock:
            return (self.ready and not self.stopped) or (
                self.share_index is not None and self.share_index.is_ready()
            )

    def stop(self) -> None:
        with self._lock:
            self.stopped = True

    # -- writes (apply-log contract, §3.2) ------------------------------------
    def add(self, ids: np.ndarray, vectors: np.ndarray, log_id: int,
            is_upsert: bool = True) -> None:
        """Apply a raft-committed VECTOR_ADD iff log_id advances
        (VectorAddHandler guard: 'if log_id > ApplyLogId',
        raft_apply_handler.cc:1115)."""
        with self._lock:
            idx = self.own_index if self.ready else None
            if idx is None:
                # split child before rebuild: writes land in the SHARED
                # parent index (same physical keyspace; both sides filter
                # searches by their own id range) — SetShareVectorIndex flow
                idx = self.active()
            if idx is None or self.stopped:
                return
            if log_id != 0 and log_id <= self.apply_log_id:
                return  # already materialized (snapshot load or replay)
            from dingo_tpu.index.recovery import RECOVERY, DeviceDegraded

            if RECOVERY.is_degraded(self.id):
                # engine (raft/WAL) holds the write; the device index is
                # awaiting re-materialization. apply_log_id does NOT
                # advance — replica digest comparisons happen at equal
                # applied indices, and this index's state describes the
                # LAST advanced log id, not this write.
                return

            def _mutate():
                with self._integrity_bracket(idx):
                    if is_upsert:
                        idx.upsert(ids, vectors)
                    else:
                        idx.add(ids, vectors)
                    # post-merge: purge absorbed-range versions from the
                    # sibling so search's sibling merge can't resurrect
                    # stale vectors
                    sib = (self.sibling_index.active()
                           if self.sibling_index else None)
                    if sib is not None and sib is not idx:
                        sib.delete(ids)
                    if log_id:
                        self.apply_log_id = log_id
                        if idx is self.own_index:
                            idx.apply_log_id = log_id
                            self._tag_integrity(idx, log_id)

            try:
                # a device OOM walks the recovery ladder (drop rerank ->
                # evict mirrors -> retry); mutations are upserts/deletes,
                # idempotent, so the whole block re-applies safely
                RECOVERY.attempt(self, self.id, _mutate, kind="write")
            except DeviceDegraded:
                return
            self.write_count += len(ids)

    def _integrity_bracket(self, idx):
        """Pending-write bracket spanning the index mutation AND its
        applied-index tag: between the ledger fold (inside idx.upsert)
        and tag_applied the (digest, applied) pair is torn, and a
        heartbeat collected in that window would read a healthy replica
        as DIVERGED — while the bracket is open the ledger withholds its
        digest vector instead (obs/integrity.py heartbeat_view)."""
        import contextlib

        from dingo_tpu.obs.integrity import INTEGRITY

        if idx is not self.own_index:
            return contextlib.nullcontext()

        @contextlib.contextmanager
        def bracket():
            INTEGRITY.note_mutation_begin(idx)
            try:
                yield
            finally:
                INTEGRITY.note_mutation_end(idx)

        return bracket()

    @staticmethod
    def _tag_integrity(idx, log_id: int) -> None:
        """Stamp the state-integrity ledger with the raft applied index
        this write advanced to — still inside the wrapper lock AND the
        pending bracket, so the (digest, applied_index) pair a heartbeat
        reads is always consistent and the coordinator can compare
        replicas at EQUAL applied indices."""
        from dingo_tpu.obs.integrity import INTEGRITY

        INTEGRITY.tag_applied(idx, log_id)

    def delete(self, ids: np.ndarray, log_id: int) -> None:
        with self._lock:
            idx = self.own_index if self.ready else None
            if idx is None:
                idx = self.active()
            if idx is None or self.stopped:
                return
            if log_id != 0 and log_id <= self.apply_log_id:
                return
            from dingo_tpu.index.recovery import RECOVERY, DeviceDegraded

            if RECOVERY.is_degraded(self.id):
                return   # same contract as add(): engine keeps the delete

            def _mutate():
                with self._integrity_bracket(idx):
                    idx.delete(ids)
                    sib = (self.sibling_index.active()
                           if self.sibling_index else None)
                    if sib is not None and sib is not idx:
                        sib.delete(ids)
                    if log_id:
                        self.apply_log_id = log_id
                        if idx is self.own_index:
                            idx.apply_log_id = log_id
                            self._tag_integrity(idx, log_id)

            try:
                RECOVERY.attempt(self, self.id, _mutate, kind="write")
            except DeviceDegraded:
                return
            self.write_count += len(ids)

    # -- reads ---------------------------------------------------------------
    def search(
        self,
        queries: np.ndarray,
        topk: int,
        filter_spec: Optional[FilterSpec] = None,
        **kw,
    ) -> List[SearchResult]:
        idx = self.active()
        if idx is None:
            raise VectorIndexError(f"vector index {self.id} not ready")
        results = idx.search(queries, topk, filter_spec, **kw)
        sibling = self.sibling_index
        if sibling is not None and sibling.active() is not None:
            # post-merge: the absorbed region's index serves its id range
            # until the target rebuild covers it (CommitMerge sibling flow)
            other = sibling.active().search(queries, topk, filter_spec, **kw)
            results = [
                _merge_results(a, b, topk, self.parameter.metric)
                for a, b in zip(results, other)
            ]
        return results

    def search_async(
        self,
        queries: np.ndarray,
        topk: int,
        filter_spec: Optional[FilterSpec] = None,
        staged=None,
        **kw,
    ) -> Callable[[], List[SearchResult]]:
        """Dispatch-now/resolve-later arm of search() for the serving
        pipeline: kernels enqueue here, the returned thunk performs the
        single host sync. The sibling-merge window (post-merge, absorbed
        region still serving its id range) falls back to a thunk around
        the serial path — merging two result sets needs both on host
        anyway, and the window is short-lived."""
        idx = self.active()
        if idx is None:
            raise VectorIndexError(f"vector index {self.id} not ready")
        sibling = self.sibling_index
        if sibling is not None and sibling.active() is not None:
            return lambda: self.search(queries, topk, filter_spec, **kw)
        dispatch = getattr(idx, "search_async", None)
        if dispatch is None:
            return lambda: idx.search(queries, topk, filter_spec, **kw)
        return dispatch(queries, topk, filter_spec, staged=staged, **kw)

    # -- policies --------------------------------------------------------------
    def need_to_save(self) -> bool:
        idx = self.own_index
        if idx is None:
            return False
        log_behind = self.apply_log_id - self.snapshot_log_id
        return self.write_count >= self.save_write_threshold or idx.need_to_save(
            log_behind
        )

    def need_to_rebuild(self) -> bool:
        idx = self.own_index
        return idx is not None and idx.need_to_rebuild()

    def get_count(self) -> int:
        idx = self.active()
        return idx.get_count() if idx else 0

    def get_memory_size(self) -> int:
        idx = self.own_index
        return idx.get_memory_size() if idx else 0

    def get_device_memory_size(self) -> int:
        """Device bytes of the OWN index (a shared parent's arrays are
        accounted on the parent's region, not double-counted here)."""
        idx = self.own_index
        return idx.get_device_memory_size() if idx else 0
