"""Product Quantization kernels: train / encode / ADC search.

Replaces faiss::ProductQuantizer + IndexIVFPQ's ADC scan used by the
reference's IVF_PQ index (src/vector/vector_index_ivf_pq.cc:337-341 —
ProductQuantizer(d, m, nbits); src/vector/vector_index_raw_ivf_pq.cc).

TPU design:
  train    — m independent on-device k-means fits (ops/kmeans.py), one per
             subspace, vmapped over the subspace axis.
  encode   — per-subspace nearest-codeword argmin; all m subspaces in one
             batched distance computation; codes stored uint8 ([n, m]).
  ADC scan — look-up-table search: LUT[b, m, ksub] of query-subvector ->
             codeword distances, then dist[b, n] = sum_m LUT[b, m, code[n, m]].
             Implemented as a chunked one-hot matmul so the inner loop is an
             MXU contraction ([chunk, m*ksub] @ [m*ksub, b]) instead of a
             gather — gathers are the slow path on TPU, matmuls are free.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from dingo_tpu.ops import kmeans as _kmeans
from dingo_tpu.ops.distance import pairwise_l2sqr
from dingo_tpu.obs.sentinel import sentinel_jit


def split_subvectors(x: jax.Array, m: int) -> jax.Array:
    """[n, d] -> [m, n, dsub]."""
    n, d = x.shape
    assert d % m == 0, f"dim {d} not divisible by m={m}"
    return jnp.transpose(x.reshape(n, m, d // m), (1, 0, 2))


def pq_train(
    x: jax.Array, m: int, ksub: int = 256, iters: int = 10, seed: int = 0
) -> jax.Array:
    """Train PQ codebooks [m, ksub, dsub] on x[n, d].

    Per-subspace farthest-first init + Lloyd; the m fits run as one vmapped
    batched program (vs faiss's sequential per-subquantizer training)."""
    import numpy as _np

    subs = split_subvectors(x.astype(jnp.float32), m)
    first = jnp.asarray(
        _np.random.default_rng(seed).integers(0, x.shape[0], size=m),
        jnp.int32,
    )

    def fit_one(sub, f):
        seeds = _kmeans.farthest_first_init(sub, f, ksub)
        c, _ = _kmeans.kmeans_fit(sub, seeds, k=ksub, iters=iters)
        return c

    return jax.vmap(fit_one)(subs, first)


@sentinel_jit("ops.pq.encode", static_argnames=("chunk",))
def pq_encode(x: jax.Array, codebooks: jax.Array, chunk: int = 8192) -> jax.Array:
    """Encode x[n, d] -> codes[n, m] uint8 (nearest codeword per subspace)."""
    m, ksub, dsub = codebooks.shape
    n = x.shape[0]
    subs = split_subvectors(x.astype(jnp.float32), m)  # [m, n, dsub]
    pad = (-n) % chunk if n > chunk else 0
    if n <= chunk:
        def enc_one(sub, cb):
            return jnp.argmin(pairwise_l2sqr(sub, cb), axis=1)
        codes = jax.vmap(enc_one)(subs, codebooks)     # [m, n]
        return codes.T.astype(jnp.uint8)
    subs = jnp.pad(subs, ((0, 0), (0, pad), (0, 0)))
    nchunks = subs.shape[1] // chunk
    subs = subs.reshape(m, nchunks, chunk, dsub).transpose(1, 0, 2, 3)

    def body(_, sub_chunk):  # [m, chunk, dsub]
        def enc_one(sub, cb):
            return jnp.argmin(pairwise_l2sqr(sub, cb), axis=1)
        return None, jax.vmap(enc_one)(sub_chunk, codebooks)  # [m, chunk]

    _, codes = jax.lax.scan(body, None, subs)          # [nchunks, m, chunk]
    codes = codes.transpose(0, 2, 1).reshape(-1, m)[:n]
    return codes.astype(jnp.uint8)


def adc_lut(q: jax.Array, codebooks: jax.Array) -> jax.Array:
    """Distance look-up tables LUT[b, m, ksub] = ||q_sub - codeword||^2."""
    m, ksub, dsub = codebooks.shape
    qs = split_subvectors(q.astype(jnp.float32), m)    # [m, b, dsub]

    def one(qsub, cb):
        return pairwise_l2sqr(qsub, cb)                # [b, ksub]

    return jnp.transpose(jax.vmap(one)(qs, codebooks), (1, 0, 2))


@sentinel_jit("ops.pq.adc_scan", static_argnames=("chunk",))
def adc_scan(
    lut: jax.Array, codes: jax.Array, chunk: int = 32768
) -> jax.Array:
    """ADC distances [b, n] from LUT[b, m, ksub] and codes[n, m].

    One-hot matmul formulation: onehot(codes)[chunk, m*ksub] @ LUT^T[m*ksub, b]
    — the contraction runs on the MXU; the one-hot is built per chunk so peak
    memory is chunk*m*ksub. (A Pallas VMEM-LUT gather kernel is the planned
    upgrade; this formulation is already compute-bound on the MXU.)
    """
    b, m, ksub = lut.shape
    n = codes.shape[0]
    lut_flat = lut.reshape(b, m * ksub).T              # [m*ksub, b]
    chunk = min(chunk, max(1024, n))
    pad = (-n) % chunk
    cp = jnp.pad(codes, ((0, pad), (0, 0)))
    nchunks = cp.shape[0] // chunk
    cc = cp.reshape(nchunks, chunk, m)
    offs = (jnp.arange(m, dtype=jnp.int32) * ksub)[None, :]

    def body(_, code_chunk):
        flat_idx = code_chunk.astype(jnp.int32) + offs          # [chunk, m]
        onehot = jax.nn.one_hot(flat_idx, m * ksub, dtype=jnp.float32)
        onehot = onehot.sum(axis=1)                             # [chunk, m*ksub]
        # f32/HIGHEST matters here: LUT entries are O(100) and m of them sum
        # into one distance — bf16 LUT noise (~0.5/term) measurably destroys
        # ADC ranking on TPU (recall@10 0.24 -> parity with CPU at f32).
        d = jnp.einsum(
            "ck,kb->cb", onehot, lut_flat,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        return None, d

    _, out = jax.lax.scan(body, None, cc)              # [nchunks, chunk, b]
    return out.reshape(-1, b)[:n].T                    # [b, n]


def pq_reconstruct(codes: jax.Array, codebooks: jax.Array) -> jax.Array:
    """Decode codes[n, m] -> approximate vectors [n, d] (for re-rank tests)."""
    m, ksub, dsub = codebooks.shape
    gathered = jax.vmap(lambda cb, c: jnp.take(cb, c, axis=0), in_axes=(0, 1))(
        codebooks, codes.astype(jnp.int32)
    )                                                   # [m, n, dsub]
    return jnp.transpose(gathered, (1, 0, 2)).reshape(codes.shape[0], m * dsub)
