"""Raft-replicated coordinator role (MetaStateMachine analog).

Reference: src/coordinator/coordinator_control.h:218 (SubmitMetaIncrementSync
routes every coordinator mutation through braft) + src/raft/meta_state_machine.h
(one state machine applying MetaIncrement records for CoordinatorControl,
TsoControl, KvControl, AutoIncrementControl alike). Round-3 VERDICT Missing #2:
our coordinator persisted to a single process's local engine — coordinator
crash = no region ops, no TSO, no meta.

TPU-first redesign note: nothing here touches the device — this is the
control plane. The reference's MetaIncrement is a protobuf diff record; ours
is a typed op record `(target, method, args, kwargs)` applied by invoking the
SAME control method bodies on every replica (command replication). That works
iff apply is deterministic, which drives three design rules:

1. **No wall clock in apply.** Every time-dependent control method takes
   `now_ms`; the proposing leader stamps it into the op (_STAMP_NOW).
   TsoControl runs with clock_init=False so its physical mark derives only
   from replicated ops (see tso.py for the failover-safety argument).
2. **Exactly-once replay.** Each op's engine writes are buffered and
   committed in ONE atomic WriteBatch together with the applied-index
   marker (_BatchedEngine), so a restarted replica skips already-applied
   entries instead of re-executing them (re-running create_region would
   allocate fresh ids and diverge from live replicas).
3. **Deterministic failures.** Exceptions raised by an op are caught,
   recorded, and re-raised only on the proposing node; buffered writes are
   committed either way so partial in-memory mutation matches the engine
   on every replica.

Reads are served from local in-memory state. The leader's state is
linearizable with respect to its own applies (propose blocks until local
apply); services route mutations to the leader and surrender NotLeader with
a hint, mirroring the store-side raft contract. Coordinator READS on a
FOLLOWER can be stale by the follower's apply lag (no read-index/leader-
lease pass — the reference serves reads through the braft leader): clients
pointed at a follower may see an old region map or job list. This is
deliberate: store-side region-epoch checks reject stale routing, and
SDK/heartbeat clients rotate to the leader on any mutation. Callers
needing linearizable meta reads should read through the leader (the
rotating client channel lands there after any write).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from dingo_tpu.common import persist
from dingo_tpu.coordinator.auto_increment import AutoIncrementControl
from dingo_tpu.coordinator.control import CoordinatorControl
from dingo_tpu.coordinator.kv_control import KvControl
from dingo_tpu.coordinator.meta import MetaControl
from dingo_tpu.coordinator.tso import TsoControl
from dingo_tpu.engine.raw_engine import CF_META, RawEngine, WriteBatch
from dingo_tpu.raft.core import NotLeader, RaftNode
from dingo_tpu.raft.log import RaftLog
from dingo_tpu.raft.transport import Transport

_KEY_APPLIED = b"METARAFT_APPLIED"

#: mutating methods per control — routed through raft; everything else is a
#: local read. An explicit whitelist (not introspection): adding a mutation
#: without listing it here would silently fork replica state.
_MUTATIONS: Dict[str, frozenset] = {
    "control": frozenset({
        "register_store", "store_heartbeat", "update_store_states",
        "next_region_id", "create_region", "requeue_cmd", "drop_region",
        "split_region", "merge_region", "on_region_merge_done",
        "on_region_split_done", "transfer_leader", "change_peer",
        "reset_sent_cmds",
    }),
    "tso": frozenset({"gen_ts", "advance_to"}),
    "kv": frozenset({
        "kv_put", "kv_delete_range", "kv_compaction",
        "lease_grant", "lease_renew", "lease_revoke", "lease_gc",
    }),
    "auto_incr": frozenset({"create", "generate", "update", "delete"}),
    "meta": frozenset({
        "create_schema", "drop_schema", "create_table", "import_table",
        "drop_table",
    }),
}

#: ops whose body consults the wall clock: the LEADER stamps now_ms at
#: propose time so all replicas apply the identical timestamp
_STAMP_NOW = frozenset({
    ("control", "register_store"), ("control", "store_heartbeat"),
    ("control", "update_store_states"),
    ("tso", "gen_ts"),
    ("kv", "kv_put"), ("kv", "lease_grant"), ("kv", "lease_renew"),
    ("kv", "lease_gc"),
})

#: sentinel distinguishing "result evicted" from a legitimate None result
_RESULT_EVICTED = object()


class _BatchedEngine:
    """Engine facade the controls write through.

    Normally passes straight through. Inside an apply, put/delete are
    buffered and flushed as ONE WriteBatch together with the applied-index
    marker — the atomicity that makes replay exactly-once. Reads always hit
    the real engine: control methods never read back their own same-op
    writes (state lives in memory; the engine is a write-behind), so
    read-your-writes inside a batch is not needed.
    """

    def __init__(self, real: RawEngine):
        self._real = real
        self._batch: Optional[WriteBatch] = None

    # -- batching protocol (state machine only) ------------------------------
    def begin(self) -> None:
        self._batch = WriteBatch()

    def commit(self, marker_key: bytes, marker_value: bytes) -> None:
        batch = self._batch
        self._batch = None
        batch.put(CF_META, marker_key, marker_value)
        self._real.write(batch)

    # -- RawEngine writes ----------------------------------------------------
    def put(self, cf: str, key: bytes, value: bytes) -> None:
        if self._batch is not None:
            self._batch.put(cf, key, value)
        else:
            self._real.put(cf, key, value)

    def delete(self, cf: str, key: bytes) -> None:
        if self._batch is not None:
            self._batch.delete(cf, key)
        else:
            self._real.delete(cf, key)

    def write(self, batch: WriteBatch) -> None:
        if self._batch is not None:
            self._batch.ops.extend(batch.ops)
        else:
            self._real.write(batch)

    # -- everything else (reads, checkpoint, close) --------------------------
    def __getattr__(self, name):
        return getattr(self._real, name)


class MetaStateMachine:
    """All coordinator-side controls over one engine, applied from the log.

    meta_state_machine.h analog: one apply path for every control; snapshot
    = the whole meta CF (the coordinator process hosts no data regions, so
    CF_META is exclusively coordinator state).
    """

    def __init__(self, engine: RawEngine, replication: int = 3):
        self._real_engine = engine
        self.engine = _BatchedEngine(engine)
        self.replication = replication
        blob = engine.get(CF_META, _KEY_APPLIED)
        self.applied_index: int = persist.loads(blob) if blob else 0
        self._build_controls()

    def _build_controls(self) -> None:
        self.control = CoordinatorControl(self.engine, self.replication)
        self.tso = TsoControl(self.engine, clock_init=False)
        self.kv = KvControl(self.engine)
        self.auto_incr = AutoIncrementControl(self.engine)
        self.meta = MetaControl(self.engine, self.control)

    # -- log application -----------------------------------------------------
    def apply(self, index: int, payload: bytes) -> Optional[Tuple[bool, Any]]:
        if index <= self.applied_index:
            return None     # replayed entry already reflected in the engine
        target, method, args, kwargs = persist.loads(payload)
        obj = getattr(self, target)
        if method not in _MUTATIONS[target]:
            raise ValueError(f"refusing non-whitelisted op {target}.{method}")
        self.engine.begin()
        try:
            try:
                result: Tuple[bool, Any] = (
                    True, getattr(obj, method)(*args, **kwargs)
                )
            except Exception as exc:  # noqa: BLE001 — deterministic on all
                result = (False, exc)  # replicas; re-raised at the proposer
        finally:
            self.applied_index = index
            self.engine.commit(_KEY_APPLIED, persist.dumps(index))
        return result

    # -- snapshot ------------------------------------------------------------
    def snapshot(self) -> bytes:
        from dingo_tpu.raft import wire

        pairs = self._real_engine.scan(CF_META, b"", None)
        return wire.encode([list(p) for p in pairs])

    def install(self, blob: bytes) -> None:
        from dingo_tpu.raft import wire

        pairs = wire.decode(blob)
        batch = WriteBatch()
        batch.delete_range(CF_META, b"", None)
        for k, v in pairs:
            batch.put(CF_META, k, v)
        self._real_engine.write(batch)
        blob2 = self._real_engine.get(CF_META, _KEY_APPLIED)
        self.applied_index = persist.loads(blob2) if blob2 else 0
        # rebuild in-memory state from the installed engine image; local
        # watch registrations do not survive (snapshot install only happens
        # on a follower that fell behind — watchers live on the leader)
        self._build_controls()


class _Proxy:
    """Duck-type stand-in for one control: reads go to local state,
    mutations become replicated ops. Services/balancers/crontabs take these
    in place of the raw control objects."""

    def __init__(self, coordinator: "RaftMetaCoordinator", target: str):
        object.__setattr__(self, "_coordinator", coordinator)
        object.__setattr__(self, "_target", target)

    def __getattr__(self, name: str):
        coordinator = self._coordinator
        target = self._target
        if name in _MUTATIONS[target]:
            def call(*args, **kwargs):
                # now_ms is keyword-only on every stamped method, so a
                # positional timestamp cannot slip past this check; an
                # explicit now_ms=0 counts as provided (None = unset)
                if (target, name) in _STAMP_NOW and \
                        kwargs.get("now_ms") is None:
                    kwargs["now_ms"] = int(time.time() * 1000)
                return coordinator.propose_op(target, name, args, kwargs)
            return call
        # reads (and constants) — resolved per call so a snapshot install
        # that rebuilds the controls is transparent
        return getattr(getattr(coordinator.sm, target), name)


class RaftMetaCoordinator:
    """One coordinator replica: MetaStateMachine behind a RaftNode.

    Exposes .control/.tso/.kv/.auto_incr/.meta proxies with the exact API
    of the raw controls; NotLeader (with a leader hint) escapes from
    mutations on a follower, mirroring the store-side write contract.
    """

    def __init__(
        self,
        node_id: str,
        peer_ids: List[str],
        transport: Transport,
        engine: RawEngine,
        replication: int = 3,
        log: Optional[RaftLog] = None,
        **raft_kw,
    ):
        self.sm = MetaStateMachine(engine, replication)
        self._results: Dict[int, Tuple[bool, Any]] = {}
        self._results_lock = threading.Lock()
        self.node = RaftNode(
            node_id, peer_ids, transport, log=log,
            apply_fn=self._apply_fn,
            snapshot_save_fn=self.sm.snapshot,
            snapshot_install_fn=self.sm.install,
            on_leader_start=self._on_leader_start,
            **raft_kw,
        )
        self.control = _Proxy(self, "control")
        self.tso = _Proxy(self, "tso")
        self.kv = _Proxy(self, "kv")
        self.auto_incr = _Proxy(self, "auto_incr")
        self.meta = _Proxy(self, "meta")

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        self.node.start()

    def stop(self) -> None:
        self.node.stop()

    def is_leader(self) -> bool:
        return self.node.is_leader()

    def leader_hint(self) -> Optional[str]:
        return self.node.leader_id

    def _on_leader_start(self, term: int) -> None:
        """New leader: re-arm commands a dead leader marked 'sent' but may
        never have delivered (see CoordinatorControl.reset_sent_cmds). Goes
        through the log like every mutation — a leader-local shortcut would
        fork replica state."""
        try:
            self.propose_op("control", "reset_sent_cmds", (), {})
        except Exception:   # noqa: BLE001 — lost leadership already; the
            pass            # next leader's own on_leader_start covers it

    # -- replicated mutation -------------------------------------------------
    def _apply_fn(self, index: int, payload: bytes) -> None:
        result = self.sm.apply(index, payload)
        if result is None:
            return
        with self._results_lock:
            self._results[index] = result
            while len(self._results) > 4096:   # bound: waiters pop their own
                self._results.pop(next(iter(self._results)))

    def propose_op(self, target: str, method: str,
                   args: tuple, kwargs: dict, timeout: float = 5.0) -> Any:
        if not self.node.is_leader():
            raise NotLeader(self.node.leader_id)
        payload = persist.dumps((target, method, list(args), kwargs))
        index = self.node.propose(payload, timeout=timeout)
        with self._results_lock:
            entry = self._results.pop(index, _RESULT_EVICTED)
        if entry is _RESULT_EVICTED:
            # the bounded buffer evicted this apply's outcome (>4096
            # concurrent proposals) — the op APPLIED, but its return value
            # and any exception it raised are gone; surface that instead
            # of silently returning None/success
            raise RuntimeError(
                f"{target}.{method}: apply result evicted under load "
                f"(op applied at index {index}; outcome unknown)")
        ok, value = entry
        if not ok:
            raise value
        return value
