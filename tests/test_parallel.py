"""Sharded store tests on the virtual 8-device CPU mesh (2D data x dim).

The multi-chip analog of the reference's in-process 3-peer raft tests:
distribution machinery exercised without real hardware."""

import numpy as np
import jax
import pytest

from dingo_tpu.ops.distance import Metric
from dingo_tpu.parallel.sharded_store import ShardedFlatStore, make_mesh


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((5000, 64)).astype(np.float32)
    ids = np.arange(5000, dtype=np.int64) * 3 + 11
    q = x[:8] + 0.01 * rng.standard_normal((8, 64)).astype(np.float32)
    d = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    want = ids[np.argsort(d, 1)[:, :10]]
    return ids, x, q, want


def test_eight_devices_present():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("shape", [(8, 1), (4, 2), (2, 4)])
def test_sharded_search_exact(data, shape):
    ids, x, q, want = data
    mesh = make_mesh(8, data=shape[0], dim=shape[1])
    store = ShardedFlatStore(mesh, dim=64)
    store.load(ids, x)
    got_ids, dists = store.search(q, 10)
    np.testing.assert_array_equal(got_ids, want)
    assert (np.diff(dists, axis=1) >= -1e-3).all()


def test_sharded_search_ip(data):
    ids, x, q, want = data
    mesh = make_mesh(8, data=4, dim=2)
    store = ShardedFlatStore(mesh, dim=64, metric=Metric.INNER_PRODUCT)
    store.load(ids, x)
    got_ids, dists = store.search(q, 5)
    d = q @ x.T
    want_ip = ids[np.argsort(-d, 1)[:, :5]]
    np.testing.assert_array_equal(got_ids, want_ip)


def test_sharded_kmeans_converges():
    rng = np.random.default_rng(1)
    centers = rng.standard_normal((8, 32)).astype(np.float32) * 3
    x = np.concatenate(
        [c + 0.05 * rng.standard_normal((100, 32)).astype(np.float32)
         for c in centers]
    )
    mesh = make_mesh(8, data=4, dim=2)
    store = ShardedFlatStore(mesh, dim=32)
    store.load(np.arange(len(x), dtype=np.int64), x)
    c, counts = store.train_kmeans(8, iters=15, seed=3)
    d = ((centers[:, None, :] - np.asarray(c)[None, :, :]) ** 2).sum(-1)
    # random seeding: most true centers recovered
    assert (d.min(axis=1) < 0.5).sum() >= 6
    assert counts.sum() == len(x)


def test_fewer_rows_than_shards():
    mesh = make_mesh(8, data=8, dim=1)
    store = ShardedFlatStore(mesh, dim=16)
    ids = np.arange(3, dtype=np.int64)
    x = np.eye(16, dtype=np.float32)[:3]
    store.load(ids, x)
    got_ids, dists = store.search(x[:2], 5)
    assert got_ids[0][0] == 0 and got_ids[1][0] == 1
    assert (got_ids[:, 3:] == -1).all()


def test_reload_returns_new_data():
    """Regression: jit cache must not bake the first load's arrays."""
    mesh = make_mesh(8, data=4, dim=2)
    store = ShardedFlatStore(mesh, dim=16)
    a = np.eye(16, dtype=np.float32)[:4]
    store.load(np.arange(4, dtype=np.int64), a)
    ids1, _ = store.search(a[:1], 1)
    assert ids1[0][0] == 0
    b = np.eye(16, dtype=np.float32)[8:12]
    store.load(np.arange(100, 104, dtype=np.int64), b)
    ids2, d2 = store.search(b[:1], 1)
    assert ids2[0][0] == 100
    assert d2[0][0] < 1e-3
