"""Closed-loop SLO parameter controller over the quality plane.

The quality plane (obs/quality.py) is the sensor: a windowed live recall
estimate with a Wilson CI per region. This module is the actuator: given
``quality.slo_recall`` and a latency budget, it walks the region's search
knobs ONE step per tick along a cheap→expensive ladder —

  rerank_factor (quantized tiers)  →  nprobe (IVF family) / ef (HNSW)
      →  precision tier (ADVISORY — a tier flip means re-encoding the
         store, so the tuner publishes the target instead of flipping)

— **tightening** (next step up) when the recall CI's upper bound dips
below the SLO (the estimate says the SLO is violated with confidence),
and **relaxing** (step down, most expensive knob first) when the lower
bound clears the SLO with margin, i.e. the region is paying for recall
nobody asked for.

Every value the tuner can choose sits on the SAME {1,1.5}x-pow2 shape
ladder the serving path buckets to (ivf_layout.shape_bucket), so a tuner
step never mints a new compiled program: steady-state recompiles stay 0
across tuner activity — the PR 5 sentinel makes this a checkable
invariant (tests/test_quality.py).

Discipline per step: apply the knob to ``index.tuning`` (consulted by the
index search paths as the default when the request doesn't pin the
parameter), then RESET the region's estimator window — evidence gathered
under the old setting must not judge the new one; the controller
naturally waits for ``min_queries`` of fresh post-step evidence before
moving again, which is the hysteresis that keeps it from thrashing.

Wired like the replica planner: ``QualityTunerRunner`` rides a store
crontab (``tuner.interval_s``), hot-reads ``tuner.enabled`` per tick, and
no-ops on stale/missing estimates — tuning on dead figures is worse than
not tuning.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Tuple

from dingo_tpu.common.log import get_logger
from dingo_tpu.common.metrics import METRICS

_log = get_logger("obs.tuner")

#: rerank_factor ladder for the quantized tiers' exact-rerank breadth
RERANK_LADDER = (1, 2, 4, 8, 16)

#: ef ceiling for the HNSW ladder (beyond this the graph walk costs more
#: than a scan)
EF_CAP = 512

#: precision tiers cheap→expensive (the advisory ladder)
PRECISION_LADDER = ("sq8", "bf16", "fp32")


def ladder_values(cap: int, floor: int = 1) -> Tuple[int, ...]:
    """Every {1,1.5}x-pow2 shape-bucket value in [floor, cap] (plus cap
    itself): the EXACT set of shapes the serving path's bucketing can
    produce, so a tuner-chosen value is always an already-warm program."""
    vals = {1, 2, 3}
    p = 4
    while p <= cap:
        vals.add(p)
        mid = 3 * (p // 2)
        if mid <= cap:
            vals.add(mid)
        p *= 2
    vals.add(int(cap))
    return tuple(sorted(v for v in vals if floor <= v <= cap))


def ladder_step(values: Tuple[int, ...], current: int,
                up: bool) -> Optional[int]:
    """Next ladder value above/below `current`; None at the bound."""
    if up:
        for v in values:
            if v > current:
                return v
        return None
    prev = None
    for v in values:
        if v >= current:
            break
        prev = v
    return prev


@dataclasses.dataclass
class TuneOp:
    region_id: int
    knob: str            # "nprobe" | "ef" | "rerank_factor" | "precision"
    old: object
    new: object
    direction: str       # "tighten" | "relax"
    applied: bool = True  # False = advisory (precision target)


class SloTuner:
    """One step of the cheap→expensive knob walk per call (crontab tick).

    Overrides land in ``index.tuning`` — the per-region serving defaults
    the search paths consult when a request doesn't pin the parameter —
    so client-pinned requests are never second-guessed."""

    def __init__(self, slo_recall: Optional[float] = None,
                 latency_budget_ms: Optional[float] = None,
                 relax_margin: float = 0.02, min_queries: int = 32,
                 quality_plane=None):
        self._slo = slo_recall
        self._budget = latency_budget_ms
        self.relax_margin = relax_margin
        self.min_queries = min_queries
        self._quality = quality_plane
        #: region -> precision target already advised (the advisory is
        #: published ONCE per stuck-at-ceiling episode, not every tick)
        self._advised: Dict[int, str] = {}

    def _flag(self, name: str, override):
        if override is not None:
            return override
        from dingo_tpu.common.config import FLAGS

        return FLAGS.get(name)

    def _plane(self):
        if self._quality is not None:
            return self._quality
        from dingo_tpu.obs.quality import QUALITY

        return QUALITY

    # -- knob model ----------------------------------------------------------
    def _knobs(self, index) -> List[Tuple[str, Tuple[int, ...], int]]:
        """(knob, ladder, current) cheap→expensive for this index kind.
        Current = tuning override if set, else the configured default —
        the tuner's first step moves FROM the operator's setting."""
        from dingo_tpu.common.config import FLAGS

        knobs: List[Tuple[str, Tuple[int, ...], int]] = []
        kind = index.index_type.value
        precision = getattr(index, "_precision", "fp32")
        # the quantized-tier rerank knob is only a LIVE actuator when the
        # index actually has a rerank cache (_rerank_shortlist returns
        # None without one) — offering it cache-less would burn tuner
        # ticks stepping a disconnected dial while the SLO stays violated
        quant_rerank = (
            precision in ("bf16", "sq8")
            and getattr(index, "_rerank_cache", None) is not None
        )
        if kind in ("ivf_flat", "ivf_pq"):
            if kind == "ivf_pq":
                # IVF_PQ's exact-rerank breadth works without a cache
                # (ADC prune + device/host row rerank)
                cur = int(index.tuning.get("rerank_factor")
                          or FLAGS.get("ivfpq_rerank_factor"))
                knobs.append(("rerank_factor", RERANK_LADDER, cur))
            elif quant_rerank:
                cur = int(index.tuning.get("rerank_factor")
                          or FLAGS.get("quantized_rerank_factor"))
                knobs.append(("rerank_factor", RERANK_LADDER, cur))
            nlist = int(getattr(index, "nlist", 0) or 1)
            cur = int(index.tuning.get("nprobe")
                      or index.parameter.default_nprobe)
            knobs.append(("nprobe", ladder_values(nlist), min(cur, nlist)))
        elif kind == "hnsw":
            cur = int(index.tuning.get("ef")
                      or getattr(index, "ef_search_default", 64))
            knobs.append(("ef", ladder_values(EF_CAP, floor=4),
                          min(cur, EF_CAP)))
        elif kind == "flat" and quant_rerank:
            cur = int(index.tuning.get("rerank_factor")
                      or FLAGS.get("quantized_rerank_factor"))
            knobs.append(("rerank_factor", RERANK_LADDER, cur))
        return knobs

    def _tighten(self, index) -> Optional[TuneOp]:
        for knob, ladder, cur in self._knobs(index):
            nxt = ladder_step(ladder, cur, up=True)
            if nxt is not None:
                return TuneOp(index.id, knob, cur, nxt, "tighten")
        # every live knob is at its ladder ceiling: the remaining lever is
        # the precision tier — advisory only (a flip re-encodes the store;
        # ROADMAP item 4's tier migration is the mechanism that will act).
        # Emitted once per stuck episode: unapplied ops don't reset the
        # estimator window, so without the memo the same advisory would
        # re-fire (counter + log line) every single tick forever.
        precision = getattr(index, "_precision", "fp32")
        if precision in PRECISION_LADDER[:-1]:
            target = PRECISION_LADDER[
                PRECISION_LADDER.index(precision) + 1]
            if self._advised.get(index.id) == target:
                return None
            self._advised[index.id] = target
            return TuneOp(index.id, "precision", precision, target,
                          "tighten", applied=False)
        return None

    def _relax(self, index) -> Optional[TuneOp]:
        for knob, ladder, cur in reversed(self._knobs(index)):
            prev = ladder_step(ladder, cur, up=False)
            if prev is not None:
                return TuneOp(index.id, knob, cur, prev, "relax")
        return None

    # -- the control step -----------------------------------------------------
    def step_index(self, index, estimate: Optional[Dict[str, float]],
                   p99_ms: Optional[float] = None) -> Optional[TuneOp]:
        """Decide + apply at most one knob step for this region. Returns
        the op (advisory ops carry applied=False), or None (no evidence,
        in-band, or at a ladder bound)."""
        slo = float(self._flag("quality_slo_recall", self._slo))
        budget = float(self._flag("tuner_latency_budget_ms", self._budget))
        if estimate is None or estimate.get("queries", 0) < self.min_queries:
            return None     # no / not enough fresh evidence: hold position
        if METRICS.gauge("qos.degrade_level",
                         region_id=index.id).get() > 0:
            # the pressure shed ladder (obs/pressure.py ShedController) is
            # actively degrading this region: tightening the very knobs it
            # just relaxed would make the two controllers fight — hold and
            # count until pressure clears (the shed controller restores
            # the saved settings on its way back down)
            METRICS.counter("quality.tuner_blocked",
                            region_id=index.id).add(1)
            return None
        from dingo_tpu.obs.quality import WindowedEstimator

        age = time.time() - float(estimate.get("newest_ts", 0.0))
        if age > 2.0 * WindowedEstimator._window_s():
            return None     # stale estimate: tuning on dead figures
        ci_lo = float(estimate["ci_low"])
        ci_hi = float(estimate["ci_high"])
        over_budget = budget > 0 and p99_ms is not None and p99_ms > budget
        if ci_hi < slo:
            # the SLO is violated with confidence — tighten, unless the
            # latency budget is already blown (then quality and latency
            # are in direct conflict: hold, count, let load shedding /
            # the operator arbitrate rather than oscillate)
            if over_budget:
                METRICS.counter("quality.tuner_blocked",
                                region_id=index.id).add(1)
                return None
            op = self._tighten(index)
        elif ci_lo > slo + self.relax_margin or (over_budget and
                                                 ci_lo > slo):
            # comfortably above the SLO (or above it AND over the latency
            # budget): walk back toward faster settings. Leaving the
            # stuck-at-ceiling regime re-arms the precision advisory.
            self._advised.pop(index.id, None)
            op = self._relax(index)
        else:
            self._advised.pop(index.id, None)   # back in band: re-arm
            return None     # in band
        if op is None:
            return None
        if op.applied:
            index.tuning[op.knob] = int(op.new)
            self._plane().reset_region(index.id)
        from dingo_tpu.obs.events import EVENTS

        EVENTS.emit(
            "tuner", index.id, op.knob, op.old, op.new,
            trigger=op.direction if op.applied else "advise",
            evidence={"ci_low": round(ci_lo, 4), "ci_high": round(ci_hi, 4),
                      "slo": slo, "p99_ms": p99_ms, "budget_ms": budget,
                      "queries": int(estimate.get("queries", 0))},
        )
        self._note(op, getattr(index, "_precision", "fp32"))
        _log.info(
            "tuner region %d: %s %s %s -> %s (recall CI [%.4f, %.4f], "
            "slo %.2f)", op.region_id, op.direction, op.knob, op.old,
            op.new, ci_lo, ci_hi, slo,
        )
        return op

    @staticmethod
    def _note(op: TuneOp, precision: str) -> None:
        METRICS.counter("quality.tuner_steps", region_id=op.region_id,
                        labels={"knob": op.knob,
                                "direction": op.direction}).add(1)
        if op.knob == "nprobe":
            METRICS.gauge("quality.tuner_nprobe",
                          region_id=op.region_id).set(float(op.new))
        elif op.knob == "ef":
            METRICS.gauge("quality.tuner_ef",
                          region_id=op.region_id).set(float(op.new))
        elif op.knob == "rerank_factor":
            METRICS.gauge("quality.tuner_rerank_factor",
                          region_id=op.region_id).set(float(op.new))
        elif op.knob == "precision":
            METRICS.gauge(
                "quality.tuner_precision_target", region_id=op.region_id
            ).set(float(PRECISION_LADDER.index(str(op.new))))


class QualityTunerRunner:
    """Store-side crontab body (server/main.py ``quality_tuner`` tab, the
    replica-planner wiring pattern): per ready region, read the live
    estimate + the measured vector_search p99 and take one tuner step.
    Hot-reads ``tuner.enabled`` per tick so operators can flip it live."""

    def __init__(self, node, tuner: Optional[SloTuner] = None,
                 crontab=None, tab_name: str = "quality_tuner"):
        self.node = node
        self.tuner = tuner or SloTuner()
        #: owning CrontabManager (when crontab-wired): tuner.interval_s
        #: is hot-changeable, so each tick re-applies it to the tab
        self._crontab = crontab
        self._tab_name = tab_name

    def tick(self) -> int:
        from dingo_tpu.common.config import FLAGS
        from dingo_tpu.obs.quality import QUALITY

        if self._crontab is not None:
            self._crontab.set_interval(
                self._tab_name, float(FLAGS.get("tuner_interval_s"))
            )
        if not bool(FLAGS.get("tuner_enabled")):
            return 0
        steps = 0
        for region in self.node.meta.get_all_regions():
            wrapper = region.vector_index_wrapper
            if wrapper is None or not wrapper.is_ready():
                continue
            index = wrapper.own_index
            if index is None:
                continue
            est = QUALITY.region_estimate(region.id)
            st = METRICS.latency("vector_search", region.id).stats()
            p99_ms = st["p99_us"] / 1000.0 if st["count"] else None
            # the per-shape cost model is a latency FLOOR: a region
            # whose typical dispatch alone cannot fit the budget is
            # over-budget evidence even before (or between) measured
            # p99 samples — the tuner must not walk recall knobs UP
            # into a latency wall the cost surface already predicts
            from dingo_tpu.obs.cost import COST, cost_enabled

            if cost_enabled():
                typical = COST.region_typical_ms(region.id)
                if typical is not None:
                    p99_ms = typical if p99_ms is None \
                        else max(p99_ms, typical)
            if self.tuner.step_index(index, est, p99_ms=p99_ms) is not None:
                steps += 1
        return steps
