"""Typed write payloads (raft proposal bodies).

Reference: src/engine/write_data.h (762 LoC) — WriteDataBuilder::BuildWrite
constructs typed RaftCmdRequest payloads (KV puts, vector adds with cf/ts/ttl,
deletes); the same payload is applied by the raft state machine on every
replica (handler/raft_apply_handler.h:29-193).

These dataclasses are the wire-neutral equivalents; `encode_write` /
`decode_write` serialize them with the typed TLV codec (raft/wire.py) for
replication — decoding network bytes can only ever produce these dataclass
shapes, never execute code (the reference gets the same property from
protobuf-typed RaftCmdRequest messages).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from dingo_tpu.raft import wire


@dataclasses.dataclass
class KvPutData:
    """PutHandler payload."""

    cf: str
    ts: int
    kvs: List[Tuple[bytes, bytes]]
    ttl_ms: int = 0


@dataclasses.dataclass
class KvDeleteData:
    """DeleteBatchHandler payload (tombstone versions)."""

    cf: str
    ts: int
    keys: List[bytes]


@dataclasses.dataclass
class KvDeleteRangeData:
    """DeleteRangeHandler payload."""

    cf: str
    ts: int
    ranges: List[Tuple[bytes, bytes]]


@dataclasses.dataclass
class VectorAddData:
    """VectorAddHandler payload (raft_apply_handler.cc:1115): vector rows +
    scalar data; handler writes data/scalar/table CFs then updates the
    in-memory index through the wrapper."""

    ts: int
    ids: np.ndarray                       # [n] int64
    vectors: np.ndarray                   # [n, d] f32
    scalars: Optional[List[Dict[str, Any]]] = None
    is_update: bool = True                # upsert vs add
    ttl_ms: int = 0
    #: per-vector serial-encoded table row -> vector_table CF (the TABLE
    #: coprocessor filter's data source, vector_reader.cc:169-232).
    #: Per entry: None = leave this vector's row untouched, b"" = clear
    #: it, bytes = replace it.
    table_values: Optional[List[Optional[bytes]]] = None


@dataclasses.dataclass
class VectorDeleteData:
    """VectorDeleteHandler payload (raft_apply_handler.cc:1374)."""

    ts: int
    ids: np.ndarray


@dataclasses.dataclass
class RebuildVectorIndexData:
    """RebuildVectorIndexHandler (raft_apply_handler.cc:1546): replicated
    marker that a rebuild cutover happened at this log position."""

    cutover_log_id: int = 0


@dataclasses.dataclass
class SplitRegionData:
    """SplitHandler payload (raft_apply_handler.cc:702)."""

    child_region_id: int
    split_key: bytes


@dataclasses.dataclass
class DocumentAddData:
    """DocumentAdd/BatchAddHandler payload (handler list,
    raft_apply_handler.h: DocumentAdd/Delete/BatchAddHandler)."""

    ts: int
    ids: List[int]
    documents: List[Dict[str, Any]]
    is_update: bool = True


@dataclasses.dataclass
class DocumentDeleteData:
    ts: int
    ids: List[int]


@dataclasses.dataclass
class MergeRegionData:
    """CommitMergeHandler payload (raft_apply_handler.cc:78-99,1021):
    target absorbs the source region's range; the source's in-memory index
    becomes the target's sibling until the target rebuilds."""

    source_region_id: int
    source_end_key: bytes


@dataclasses.dataclass
class RegionInstallData:
    """Whole-region wipe + restore (RegionImport) routed through the raft
    log: every replica applies the install at the same log position, so
    concurrent raft writes order strictly before or after it and replicas
    can never diverge (the off-log `region_install` push this replaces
    left any replica that applied a concurrent write mid-push permanently
    forked)."""

    cfs: List[Tuple[str, List[Tuple[bytes, bytes]]]]


@dataclasses.dataclass
class TxnRaftData:
    """TxnHandler payload (raft_apply_handler_txn.cc): pre-encoded CF writes
    produced by the Percolator helper (engine/txn.py)."""

    puts: List[Tuple[str, bytes, bytes]]
    deletes: List[Tuple[str, bytes]]


WriteData = Any  # union of the payload dataclasses above

_PAYLOAD_TYPES = {
    cls.__name__: cls
    for cls in (
        KvPutData, KvDeleteData, KvDeleteRangeData, VectorAddData,
        VectorDeleteData, RebuildVectorIndexData, SplitRegionData,
        DocumentAddData, DocumentDeleteData, MergeRegionData,
        RegionInstallData, TxnRaftData,
    )
}

def encode_write(data: WriteData) -> bytes:
    """Raft proposal payload bytes for any of the dataclasses above."""
    fields = {
        f.name: wire.to_plain(getattr(data, f.name))
        for f in dataclasses.fields(data)
    }
    return wire.encode({"kind": type(data).__name__, "fields": fields})


def decode_write(payload: bytes) -> WriteData:
    """Inverse of encode_write; raises wire.WireError on malformed bytes.
    Decoded ndarrays are read-only views over the wire buffer; tuples decode
    as lists (apply handlers only iterate/unpack)."""
    d = wire.decode(payload)
    if not isinstance(d, dict) or "kind" not in d or "fields" not in d:
        raise wire.WireError("decode_write: not a WriteData envelope")
    cls = _PAYLOAD_TYPES.get(d["kind"])
    if cls is None:
        raise wire.WireError(f"decode_write: unknown payload kind {d['kind']!r}")
    fields = d["fields"]
    if not isinstance(fields, dict):
        raise wire.WireError("decode_write: fields must be a dict")
    names = {f.name for f in dataclasses.fields(cls)}
    if set(fields) - names:
        raise wire.WireError(
            f"decode_write: unexpected fields {set(fields) - names}"
        )
    try:
        return cls(**{k: wire.from_plain(v) for k, v in fields.items()})
    except (TypeError, ValueError) as e:
        raise wire.WireError(f"decode_write: bad fields: {e}") from e
