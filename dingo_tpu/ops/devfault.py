"""Device-fault shim: synthetic RESOURCE_EXHAUSTED on kernel dispatch.

Real HBM OOMs surface as XlaRuntimeError("RESOURCE_EXHAUSTED: ...") at
kernel dispatch time and are classified by ``obs/hbm.looks_like_oom``.
They are also nearly impossible to produce on demand — on the CPU smoke
arm there is no HBM at all. This shim injects an indistinguishable
failure at the ONE chokepoint every persistent device dispatch already
passes through (``sentinel_jit``, obs/sentinel.py), so the whole recovery
ladder — drop caches, evict mirrors, retry, degrade to the host path —
is exercised end-to-end by the chaos harness with real exceptions on the
real code path, deterministically.

Disarmed cost: one attribute read per dispatch (``_armed`` int check,
no lock). Arm with ``DEVFAULT.arm(n)`` to fail the next n dispatches, or
``DEVFAULT.arm(n, kernel_substr="flat")`` to fail only matching kernels.
"""

from __future__ import annotations

import threading
from typing import Optional


class InjectedDeviceFault(RuntimeError):
    """Synthetic device allocation failure. The message carries the
    RESOURCE_EXHAUSTED marker so ``obs/hbm.looks_like_oom`` classifies it
    exactly like a real XlaRuntimeError OOM — recovery code cannot (and
    must not) tell them apart."""


class DeviceFaultShim:
    def __init__(self):
        self._lock = threading.Lock()
        self._armed = 0
        self._kernel_substr: Optional[str] = None
        self.fired = 0

    def arm(self, n: int = 1, kernel_substr: Optional[str] = None) -> None:
        """Fail the next `n` sentinel dispatches (optionally only kernels
        whose name contains `kernel_substr`)."""
        with self._lock:
            self._armed = int(n)
            self._kernel_substr = kernel_substr

    def disarm(self) -> None:
        with self._lock:
            self._armed = 0
            self._kernel_substr = None

    def armed(self) -> int:
        return self._armed

    def maybe_fail(self, kernel: str) -> None:
        """Called by the sentinel_jit wrapper before dispatch."""
        if not self._armed:           # disarmed fast path: no lock
            return
        with self._lock:
            if not self._armed:
                return
            if self._kernel_substr is not None \
                    and self._kernel_substr not in kernel:
                return
            self._armed -= 1
            self.fired += 1
        from dingo_tpu.common.metrics import METRICS

        METRICS.counter("fault.injected",
                        labels={"point": "device_dispatch"}).add(1)
        raise InjectedDeviceFault(
            f"RESOURCE_EXHAUSTED: injected device fault at {kernel} "
            "(out of memory while trying to allocate — synthetic)"
        )


#: process-global shim (one device, one dispatch chokepoint)
DEVFAULT = DeviceFaultShim()
