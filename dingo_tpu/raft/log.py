"""Raft log storage.

Reference: src/log/ — RocksLogStorage (multi-region raft log in one RocksDB,
rocks_log_storage.h:180) and SegmentLogStorage (segment files). Key extra
duty: the vector index catch-up path reads committed data entries straight
from this log (GetDataEntries, vector_index_manager.cc:796), so the log
keeps entries until a snapshot truncates them.

Here: an in-memory list with an optional append-only file behind it
(segment-style); entries are (term, payload_bytes). Index 0 is a sentinel —
raft indices are 1-based like the paper.
"""

from __future__ import annotations

import os
import struct

from dingo_tpu.raft import wire
import threading
from typing import List, Optional, Tuple

_REC_MAGIC = 0x5AF7106D


class RaftLog:
    def __init__(self, path: Optional[str] = None):
        self._lock = threading.RLock()
        # entries[i] corresponds to raft index first_index + i
        self._entries: List[Tuple[int, bytes]] = []
        self.first_index = 1          # index of entries[0]
        self.snapshot_index = 0       # last index covered by a snapshot
        self.snapshot_term = 0
        self._hard_term = 0           # persisted (term, voted_for)
        self._hard_vote: Optional[str] = None
        self._path = path
        self._fh = None
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._replay()
            self._fh = open(path, "ab")

    # -- persistence ---------------------------------------------------------
    def _replay(self) -> None:
        if not os.path.exists(self._path):
            return
        good = 0
        with open(self._path, "rb") as f:
            while True:
                hdr = f.read(8)
                if len(hdr) < 8:
                    break
                magic, ln = struct.unpack(">II", hdr)
                if magic != _REC_MAGIC:
                    break
                blob = f.read(ln)
                if len(blob) < ln:
                    break
                try:
                    rec = wire.decode(blob)
                except wire.WireError:
                    break  # torn/corrupt tail
                kind = rec[0]
                if kind == "append":
                    _, index, term, payload = rec
                    self._truncate_from_unlocked(index)
                    self._entries.append((term, payload))
                elif kind == "compact":
                    _, index, term = rec
                    self._apply_compaction(index, term)
                elif kind == "hard":
                    _, self._hard_term, self._hard_vote = rec
                good = f.tell()
        # truncate a torn tail so post-recovery appends are not written
        # after garbage (unreachable by the next replay = acked-entry loss)
        if os.path.getsize(self._path) > good:
            with open(self._path, "r+b") as f:
                f.truncate(good)

    def _write_rec(self, rec) -> None:
        if self._fh is None:
            return
        blob = wire.encode(list(rec))
        self._fh.write(struct.pack(">II", _REC_MAGIC, len(blob)) + blob)
        self._fh.flush()

    # -- hard state (term/vote survive restart: raft election safety) -------
    def hard_state(self):
        with self._lock:
            return self._hard_term, self._hard_vote

    def set_hard_state(self, term: int, voted_for: Optional[str]) -> None:
        with self._lock:
            self._hard_term, self._hard_vote = term, voted_for
            self._write_rec(("hard", term, voted_for))

    # -- core API ------------------------------------------------------------
    def last_index(self) -> int:
        with self._lock:
            return self.first_index + len(self._entries) - 1 if self._entries \
                else self.snapshot_index

    def last_term(self) -> int:
        with self._lock:
            if self._entries:
                return self._entries[-1][0]
            return self.snapshot_term

    def term_at(self, index: int) -> Optional[int]:
        with self._lock:
            if index == 0:
                return 0
            if index == self.snapshot_index:
                return self.snapshot_term
            i = index - self.first_index
            if 0 <= i < len(self._entries):
                return self._entries[i][0]
            return None

    def entry_at(self, index: int) -> Optional[Tuple[int, bytes]]:
        with self._lock:
            i = index - self.first_index
            if 0 <= i < len(self._entries):
                return self._entries[i]
            return None

    def append(self, term: int, payload: bytes) -> int:
        with self._lock:
            index = self.last_index() + 1
            self._entries.append((term, payload))
            self._write_rec(("append", index, term, payload))
            return index

    def put_at(self, index: int, term: int, payload: bytes) -> None:
        """Follower append with conflict truncation."""
        with self._lock:
            self._truncate_from_unlocked(index)
            assert index == self.last_index() + 1, (index, self.last_index())
            self._entries.append((term, payload))
            self._write_rec(("append", index, term, payload))

    def _truncate_from_unlocked(self, index: int) -> None:
        i = index - self.first_index
        if i < len(self._entries):
            del self._entries[max(i, 0):]

    def entries_from(self, start: int, max_count: int = 256):
        """[(index, term, payload)] from `start`, bounded."""
        with self._lock:
            out = []
            idx = max(start, self.first_index)
            while idx <= self.last_index() and len(out) < max_count:
                term, payload = self._entries[idx - self.first_index]
                out.append((idx, term, payload))
                idx += 1
            return out

    def get_data_entries(self, start: int, end: int):
        """Committed payloads in [start, end] — the vector-index catch-up
        feed (vector_index_manager.cc:796 GetDataEntries)."""
        with self._lock:
            lo = max(start, self.first_index)
            if end < lo:
                return []
            return self.entries_from(lo, max_count=end - lo + 1)

    # -- compaction / snapshot ----------------------------------------------
    def _apply_compaction(self, index: int, term: int) -> None:
        keep_from = index + 1
        i = keep_from - self.first_index
        if i > 0:
            self._entries = self._entries[i:] if i <= len(self._entries) else []
            self.first_index = keep_from
        self.snapshot_index = index
        self.snapshot_term = term
        self.first_index = max(self.first_index, keep_from)

    def compact(self, index: int) -> None:
        """Drop entries <= index (after a snapshot covers them)."""
        with self._lock:
            term = self.term_at(index) or self.snapshot_term
            self._apply_compaction(index, term)
            self._write_rec(("compact", index, term))

    def install_snapshot_mark(self, index: int, term: int) -> None:
        """Follower received a full snapshot: reset the log to start after it."""
        with self._lock:
            self._entries = []
            self.first_index = index + 1
            self.snapshot_index = index
            self.snapshot_term = term
            self._write_rec(("compact", index, term))

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None
