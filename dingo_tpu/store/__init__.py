"""Store-side runtime: region objects, meta manager, region controller,
heartbeat. Mirrors reference src/meta/ + src/store/."""
