"""dingo-tpu: a TPU-native rebuild of dingodb/dingo-store.

A distributed Key-Value storage system on multi-Raft replication groups whose
Index role serves high-dimensional vector search. The reference's ANN compute
path (faiss + src/simd AVX kernels) is rebuilt TPU-first: region-local vectors
live in (sharded) JAX arrays, and distance / top-k / IVF / PQ kernels run as
jit'd XLA / Pallas programs.

Layering (mirrors SURVEY.md §1, TPU-first re-design):

    server/       RPC services (grpc)           <- reference src/server/
    engine/       Storage facade + engines      <- reference src/engine/
    raft/         Raft consensus + state machine<- reference src/raft, src/log
    mvcc/         MVCC codec / reader / TSO     <- reference src/mvcc/
    index/        Vector index families         <- reference src/vector/
    ops/          TPU kernels (XLA/Pallas)      <- reference src/simd/ + faiss
    parallel/     Mesh sharding / collectives   <- (TPU-native; no reference
                                                   analog: replaces ThreadPool
                                                   batch parallelism)
    coordinator/  Cluster control plane         <- reference src/coordinator/
    store/        Store-side control            <- reference src/store/
    coprocessor/  Scalar filter / aggregation   <- reference src/coprocessor/
    common/       Runtime utils (config, crontab, failpoint, tracker, metrics)
"""

__version__ = "0.1.0"
